"""Static sharding analysis: spec lint, IR lint, composition matrix, CLI.

Acceptance pins (ISSUE 1): the lint CLI flags three seeded violations —
unknown mesh axis, oversized replicated-by-default param, fsdp×1f1b
seq2seq composition — as ``error``, and reports zero error-level findings
on every BASELINE.md config.  Plus the repo AST lint and the analysis-CLI
smoke run (satellite: CI / tooling).
"""

import json

import jax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llms_example_tpu.analysis import composition
from distributed_llms_example_tpu.analysis.findings import Finding, has_errors
from distributed_llms_example_tpu.analysis.ir_lint import scan_hlo_text
from distributed_llms_example_tpu.analysis.lint import main as lint_main
from distributed_llms_example_tpu.analysis.spec_lint import lint_sharding_rules
from distributed_llms_example_tpu.core.config import MeshConfig, parse_mesh_arg
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.parallel.sharding import (
    ShardingRules,
    default_rules,
    find_dead_rules,
    shard_params,
)


def _codes(findings, severity=None):
    return [
        f.code for f in findings if severity is None or f.severity == severity
    ]


def _abstract_llama_params():
    from distributed_llms_example_tpu.models.registry import load_model

    lm = load_model("llama-test", load_weights=False)
    return jax.eval_shape(lambda: lm.init_params(0))


# ---------------------------------------------------------------------------
# pass 1 — spec lint
# ---------------------------------------------------------------------------

def test_spec_lint_unknown_axis_names_the_typo():
    rules = ShardingRules(rules=[(r"mlp/.*proj/kernel", P("fsdp", "tensro"))])
    findings = lint_sharding_rules(
        rules, {"fsdp": 2, "tensor": 2}, _abstract_llama_params()
    )
    errs = [f for f in findings if f.code == "unknown-mesh-axis"]
    assert errs and errs[0].severity == "error"
    assert "tensro" in errs[0].message and "tensor" in errs[0].message  # suggestion


def test_spec_lint_duplicate_axis():
    rules = ShardingRules(rules=[(r"kernel", P("tensor", "tensor"))])
    findings = lint_sharding_rules(rules, {"tensor": 2}, _abstract_llama_params())
    assert "duplicate-spec-axis" in _codes(findings, "error")


def test_spec_lint_dead_rule_is_warning():
    rules = ShardingRules(
        rules=[
            (r"no_such_param/anywhere", P("fsdp")),
            (r"kernel", P("fsdp", "tensor")),
        ]
    )
    findings = lint_sharding_rules(
        rules, {"fsdp": 2, "tensor": 2}, _abstract_llama_params()
    )
    dead = [f for f in findings if f.code == "dead-rule"]
    assert len(dead) == 1 and dead[0].severity == "warning"
    assert "no_such_param" in dead[0].message


def test_spec_lint_oversized_replicated_default():
    # no rules at all: every matmul weight falls through to replicated
    findings = lint_sharding_rules(
        ShardingRules(rules=[]),
        {"fsdp": 8},
        _abstract_llama_params(),
        replicated_bytes_threshold=1024,  # tiny model needs a tiny bar
    )
    over = [f for f in findings if f.code == "oversized-replicated-param"]
    assert over and all(f.severity == "error" for f in over)


def test_spec_lint_oversized_silent_on_pure_data_mesh():
    # pure DP replicates params BY DESIGN — never an error
    findings = lint_sharding_rules(
        ShardingRules(rules=[]),
        {"data": 8},
        _abstract_llama_params(),
        replicated_bytes_threshold=1024,
    )
    assert "oversized-replicated-param" not in _codes(findings)


def test_spec_lint_ragged_dim_warns():
    import numpy as np

    params = {"embed": jax.ShapeDtypeStruct((50265, 64), np.dtype("float32"))}
    rules = ShardingRules(rules=[(r"embed", P(("tensor", "fsdp"), None))])
    findings = lint_sharding_rules(rules, {"tensor": 2, "fsdp": 2}, params)
    ragged = [f for f in findings if f.code == "ragged-dim-replicated"]
    assert ragged and ragged[0].severity == "warning"


def test_default_rules_clean_on_llama_fsdp():
    findings = lint_sharding_rules(
        default_rules(), {"fsdp": 8}, _abstract_llama_params()
    )
    assert not has_errors(findings)


# ---------------------------------------------------------------------------
# pass 3 — composition matrix
# ---------------------------------------------------------------------------

BAD_CASES = [
    # (row id, family, schedule, mesh axes, flags)
    ("grad-accum-pipelined", "llama", "gpipe", {"stage": 2, "data": 2},
     ("pipelined", "grad_accum")),
    ("seq2seq-1f1b-fsdp", "bart", "1f1b", {"stage": 2, "fsdp": 2}, ("pipelined",)),
    ("seq2seq-1f1b-fsdp", "t5", "1f1b", {"stage": 4, "fsdp": 2}, ("pipelined",)),
    ("seq2seq-interleaved", "bart", "interleaved", {"stage": 2}, ("pipelined",)),
    ("seq2seq-pipeline-sequence", "t5", "gpipe", {"stage": 2, "sequence": 2}, ("pipelined",)),
    ("pipeline-sequence-moe", "llama", "gpipe", {"stage": 2, "sequence": 2}, ("pipelined", "moe")),
    ("fused-ce-seq2seq", "bart", None, {"data": 8}, ("fused_ce",)),
    ("fused-ce-model-axes", "llama", None, {"tensor": 2}, ("fused_ce",)),
    ("ring-seq2seq-pipeline", "t5", "gpipe", {"stage": 2, "sequence": 2}, ("pipelined", "ring")),
    ("dense-attention-stage-sequence", "llama", "1f1b", {"stage": 2, "sequence": 2},
     ("pipelined", "forced_dense_attention")),
]


@pytest.mark.parametrize("row_id,family,schedule,axes,flags", BAD_CASES)
def test_every_known_bad_combo_fires(row_id, family, schedule, axes, flags):
    bad = composition.failing_combos(
        family=family, schedule=schedule, mesh_axes=axes, flags=flags
    )
    assert row_id in [r.id for r in bad]
    # validate raises the FIRST failing row's reason (overlapping combos —
    # e.g. ring × seq2seq × pipeline also trips the sequence row — report
    # the most specific/earliest table entry)
    with pytest.raises(ValueError) as ei:
        composition.validate_composition(
            family=family, schedule=schedule, mesh_axes=axes, flags=flags
        )
    assert str(ei.value) == bad[0].reason


def test_good_combos_do_not_fire():
    for family, schedule, axes, flags in [
        ("llama", "1f1b", {"stage": 2, "fsdp": 2, "data": 2}, ("pipelined",)),
        ("bart", "gpipe", {"stage": 2, "fsdp": 2, "data": 2}, ("pipelined",)),
        ("bart", "1f1b", {"stage": 2, "data": 2, "tensor": 2}, ("pipelined",)),
        ("llama", None, {"data": 4, "fsdp": 2}, ("fused_ce",)),
        ("t5", None, {"data": 4, "sequence": 2}, ()),
        # in-step accumulation composes with every GSPMD mesh; only
        # stage>1 (the pipeline's own microbatching) is condemned
        ("llama", None, {"data": 4, "fsdp": 2}, ("grad_accum",)),
        ("bart", None, {"data": 2, "fsdp": 2, "tensor": 2}, ("grad_accum",)),
    ]:
        composition.validate_composition(
            family=family, schedule=schedule, mesh_axes=axes, flags=flags
        )


def test_executor_guard_uses_table_message():
    """The deep guard in the seq2seq executor raises the table row's text
    (it cannot drift from the adapter-construction message)."""
    import jax.numpy as jnp

    from distributed_llms_example_tpu.parallel.pipeline_seq2seq import (
        pipeline_value_and_grad_seq2seq,
    )

    mesh = build_mesh(MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1))
    with pytest.raises(ValueError, match="fsdp"):
        pipeline_value_and_grad_seq2seq(
            None, None, None, {"w": jnp.zeros((2, 1))}, {"w": jnp.zeros((2, 1))},
            {}, jnp.zeros((4, 4, 8)), jnp.zeros((4, 2, 8)), {}, {},
            mesh=mesh, num_microbatches=2,
        )


def test_adapters_reject_known_bad_at_construction():
    """Satellite: every known-bad combo reachable through an adapter ctor
    is rejected at construction with the table-driven message."""
    from distributed_llms_example_tpu.models.bart import PipelinedBart
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.models.registry import (
        BART_CONFIGS,
        LLAMA_CONFIGS,
        T5_CONFIGS,
    )
    from distributed_llms_example_tpu.models.t5 import PipelinedT5

    fsdp_mesh = build_mesh(MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1))
    seq_mesh = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=2, tensor=1))

    # seq2seq 1f1b × fsdp at stage > 1 — both families
    with pytest.raises(ValueError, match="fsdp"):
        PipelinedBart(BART_CONFIGS["bart-test"], fsdp_mesh, schedule="1f1b")
    with pytest.raises(ValueError, match="fsdp"):
        PipelinedT5(T5_CONFIGS["t5-test"], fsdp_mesh, schedule="1f1b")
    # interleaved is decoder-only
    with pytest.raises(ValueError, match="interleaved"):
        PipelinedBart(BART_CONFIGS["bart-test"], fsdp_mesh, schedule="interleaved")
    # seq2seq pipeline × sequence parallelism
    with pytest.raises(ValueError, match="sequence"):
        PipelinedT5(T5_CONFIGS["t5-test"], seq_mesh, schedule="gpipe")
    # MoE × sequence under the pipeline
    with pytest.raises(ValueError, match="MoE"):
        PipelinedLlama(LLAMA_CONFIGS["mixtral-test"], seq_mesh, schedule="gpipe")
    # same meshes construct fine on allowed schedules/families
    PipelinedBart(BART_CONFIGS["bart-test"], fsdp_mesh, schedule="gpipe")
    PipelinedLlama(LLAMA_CONFIGS["llama-test"], seq_mesh, schedule="gpipe")


# ---------------------------------------------------------------------------
# pass 2 — IR scanner (pure text)
# ---------------------------------------------------------------------------

_SYNTH_HLO = """\
HloModule synth

ENTRY %main {
  %p0 = bf16[64,64]{1,0} parameter(0)
  %c1 = f32[64,64]{1,0} convert(bf16[64,64]{1,0} %p0)
  %p1 = f32[64,64]{1,0} parameter(1)
  %dot.1 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %c1, f32[64,64]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ag.1 = f32[4096,4096]{1,0} all-gather(f32[512,4096]{1,0} %p1), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %ar.1 = f32[64]{0} all-reduce(f32[64]{0} %p1), replica_groups={{0},{1},{2},{3}}, to_apply=%add
  %ar.2 = f32[64]{0} all-reduce(f32[64]{0} %p1), replica_groups={{0,1},{2,3}}, to_apply=%add
  ROOT %t.1 = f32[64,64]{1,0} tuple(%dot.1)
}
"""


def test_ir_scanner_flags_gather_on_unsharded_mesh():
    findings = scan_hlo_text(_SYNTH_HLO, mesh_axes={"data": 8})
    gather = [f for f in findings if f.code == "full-param-all-gather"]
    assert gather and gather[0].severity == "error"
    assert gather[0].context["max_bytes"] == 4096 * 4096 * 4


def test_ir_scanner_mega_gather_on_fsdp_mesh():
    findings = scan_hlo_text(
        _SYNTH_HLO, mesh_axes={"fsdp": 8}, largest_param_bytes=1024 * 1024
    )
    assert "full-param-all-gather" not in _codes(findings)  # fsdp gathers are the design
    mega = [f for f in findings if f.code == "fused-mega-all-gather"]
    assert mega and mega[0].severity == "warning"


def test_ir_scanner_precision_promotion():
    findings = scan_hlo_text(
        _SYNTH_HLO, mesh_axes={"fsdp": 8}, promotion_smell=("bf16", "f32")
    )
    promo = [f for f in findings if f.code == "matmul-precision-promotion"]
    assert promo and "dot.1" in promo[0].context["instructions"]
    # fp32 policy has nothing to violate
    clean = scan_hlo_text(_SYNTH_HLO, mesh_axes={"fsdp": 8}, promotion_smell=None)
    assert "matmul-precision-promotion" not in _codes(clean)


def test_ir_scanner_degenerate_collective():
    findings = scan_hlo_text(_SYNTH_HLO, mesh_axes={"fsdp": 8})
    degen = [f for f in findings if f.code == "degenerate-collective"]
    assert degen and degen[0].context["instructions"] == ["ar.1"]  # ar.2 is real
    census = [f for f in findings if f.code == "collective-census"][0]
    assert census.context["census"] == {"all-gather": 1, "all-reduce": 2}


_ASYNC_HLO = """\
HloModule async

ENTRY %main {
  %p1 = f32[512,4096]{1,0} parameter(0)
  %ags.1 = (f32[512,4096]{1,0}, f32[4096,4096]{1,0}) all-gather-start(f32[512,4096]{1,0} %p1), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %agd.1 = f32[4096,4096]{1,0} all-gather-done((f32[512,4096]{1,0}, f32[4096,4096]{1,0}) %ags.1)
  %ars.1 = f32[64]{0} all-reduce-start(f32[64]{0} %p1), replica_groups={{0},{1},{2},{3}}, to_apply=%add
  ROOT %t.1 = f32[4096,4096]{1,0} tuple(%agd.1)
}
"""


def test_ir_scanner_parses_async_tuple_collectives():
    """TPU HLO emits async pairs with tuple-shaped -start defs; the
    scanner must size them (max tuple element = the gathered result) and
    see their replica groups."""
    findings = scan_hlo_text(_ASYNC_HLO, mesh_axes={"data": 8})
    gather = [f for f in findings if f.code == "full-param-all-gather"]
    assert gather and gather[0].context["max_bytes"] == 4096 * 4096 * 4
    degen = [f for f in findings if f.code == "degenerate-collective"]
    assert degen and degen[0].context["instructions"] == ["ars.1"]
    census = [f for f in findings if f.code == "collective-census"][0]
    assert census.context["census"] == {
        "all-gather-start": 1, "all-reduce-start": 1,
    }


_HOST_XFER_HLO = """\
HloModule leaky

ENTRY %main {
  %p1 = f32[64,64]{1,0} parameter(0)
  %send.1 = (f32[64,64]{1,0}, u32[], token[]) send(f32[64,64]{1,0} %p1, token[] %tok), channel_id=1, is_host_transfer=true
  %send.2 = (f32[64,64]{1,0}, u32[], token[]) send(f32[64,64]{1,0} %p1, token[] %tok), channel_id=2
  %out.1 = token[] outfeed(f32[64,64]{1,0} %p1, token[] %tok)
  %cc.1 = f32[64,64]{1,0} custom-call(f32[64,64]{1,0} %p1), custom_call_target="MoveToHost"
  ROOT %t.1 = f32[64,64]{1,0} tuple(%p1)
}
"""


def test_ir_scanner_host_transfer_in_step():
    """The ROADMAP 'host-transfer ops inside the step body' smell: outfeed,
    is_host_transfer-attributed send, and MoveToHost custom-calls are
    errors; an UN-attributed send (device-to-device channel traffic) is
    not flagged."""
    findings = scan_hlo_text(_HOST_XFER_HLO, mesh_axes={"data": 8})
    host = [f for f in findings if f.code == "host-transfer-in-step"]
    assert host and host[0].severity == "error"
    flagged = host[0].context["instructions"]
    assert "send.1" in flagged and "out.1" in flagged and "cc.1" in flagged
    assert "send.2" not in flagged


def test_ir_scanner_host_transfer_clean_on_synth_and_real_step():
    # the synthetic collective program carries no host traffic
    assert "host-transfer-in-step" not in _codes(
        scan_hlo_text(_SYNTH_HLO, mesh_axes={"data": 8})
    )


def test_policy_promotion_smell():
    from distributed_llms_example_tpu.core.precision import Policy, parse_dtype

    assert Policy(compute_dtype=parse_dtype("bfloat16")).matmul_promotion_smell() == ("bf16", "f32")
    assert Policy(compute_dtype=parse_dtype("float32")).matmul_promotion_smell() is None


# ---------------------------------------------------------------------------
# the CLI — seeded violations + BASELINE configs
# ---------------------------------------------------------------------------

def _run_cli(capsys, *argv):
    rc = lint_main(["--json", *argv])
    out = capsys.readouterr().out
    findings = [
        json.loads(line) for line in out.splitlines()
        if line.startswith("{") and json.loads(line).get("event") == "lint_finding"
    ]
    return rc, findings


def test_cli_seeded_unknown_mesh_axis(capsys):
    rc, findings = _run_cli(capsys, "--model", "t5-small", "--mesh", "datta=8")
    assert rc == 1
    f = [x for x in findings if x["code"] == "unknown-mesh-axis"]
    assert f and f[0]["severity"] == "error" and "data" in f[0]["message"]


def test_cli_seeded_oversized_replicated(capsys):
    rc, findings = _run_cli(
        capsys, "--model", "llama-2-7b", "--mesh", "fsdp=8",
        "--rules-json", "[]", "--no-ir",
    )
    assert rc == 1
    assert any(
        f["code"] == "oversized-replicated-param" and f["severity"] == "error"
        for f in findings
    )


def test_cli_seeded_seq2seq_1f1b_fsdp(capsys):
    rc, findings = _run_cli(
        capsys, "--model", "bart-large-cnn", "--mesh", "stage=2,fsdp=2,data=2",
        "--pipeline-schedule", "1f1b", "--no-ir",
    )
    assert rc == 1
    assert any(
        f["code"] == "seq2seq-1f1b-fsdp" and f["severity"] == "error"
        for f in findings
    )


# every BASELINE.md config must come out clean (error-free)
BASELINE_CONFIGS = [
    ("t5-small", "data=1"),
    ("t5-base", "data=-1"),
    ("bart-large-cnn", "data=8"),
    ("flan-t5-xl", "fsdp=8"),
    ("llama-2-7b", "fsdp=8"),
]


@pytest.mark.parametrize("model,mesh", BASELINE_CONFIGS)
def test_cli_baseline_configs_error_free(capsys, model, mesh):
    rc, findings = _run_cli(capsys, "--model", model, "--mesh", mesh, "--no-ir")
    assert rc == 0
    assert [f for f in findings if f["severity"] == "error"] == []


def test_cli_ir_pass_smoke(capsys):
    """The full three-pass run, AOT compile included, on the tiny config."""
    rc, findings = _run_cli(
        capsys, "--model", "t5-test", "--mesh", "data=2,fsdp=2,tensor=2",
        "--batch", "8", "--src-len", "64", "--tgt-len", "16",
    )
    assert rc == 0
    census = [f for f in findings if f["code"] == "collective-census"]
    assert census, "IR pass should have run and reported its census"
    assert [f for f in findings if f["severity"] == "error"] == []


def test_cli_strict_promotes_warnings(capsys):
    # the stock multi-family rule set's dead entries are info (by design),
    # so --strict stays green on a clean default config...
    rc, findings = _run_cli(
        capsys, "--model", "t5-small", "--mesh", "data=1", "--no-ir", "--strict"
    )
    assert rc == 0
    assert all(f["severity"] == "info" for f in findings if f["code"] == "dead-rule")
    # ...but a CUSTOM rule set's dead rule is a warning, and --strict
    # fails on it
    custom = '[["encoder/.*/kernel", ["fsdp", "tensor"]], ["typo/never", ["fsdp"]]]'
    rc, findings = _run_cli(
        capsys, "--model", "t5-small", "--mesh", "data=1",
        "--rules-json", custom, "--no-ir",
    )
    assert rc == 0  # dead rule is only a warning
    assert any(
        f["code"] == "dead-rule" and f["severity"] == "warning" for f in findings
    )
    rc, _ = _run_cli(
        capsys, "--model", "t5-small", "--mesh", "data=1",
        "--rules-json", custom, "--no-ir", "--strict",
    )
    assert rc == 1


def test_startup_lint_runs_from_train_config():
    from distributed_llms_example_tpu.analysis.lint import startup_lint
    from distributed_llms_example_tpu.core.config import TrainConfig

    cfg = TrainConfig(model_ckpt="t5-test", mesh=MeshConfig(data=2, fsdp=1))
    findings = startup_lint(cfg)
    assert findings and not has_errors(findings)
    # a known-bad combo surfaces as an error finding, not a crash
    bad = TrainConfig(
        model_ckpt="bart-test",
        pipeline_schedule="1f1b",
        mesh=MeshConfig(stage=2, fsdp=2, data=2),
    )
    assert has_errors(startup_lint(bad))


# ---------------------------------------------------------------------------
# satellites: mesh-axis typo, dead-rule warning, memory-audit --strict,
# repo AST lint
# ---------------------------------------------------------------------------

def test_parse_mesh_arg_names_typo_with_suggestion():
    with pytest.raises(ValueError, match="did you mean 'data'"):
        parse_mesh_arg("datta=2")
    with pytest.raises(ValueError, match="valid axes"):
        parse_mesh_arg("bogus=2")


def test_shard_params_warns_on_dead_rules(capsys, dp_mesh):
    import numpy as np

    params = {"layer": {"kernel": np.zeros((8, 8), np.float32)}}
    rules = ShardingRules(rules=[
        (r"kernel", P()),
        (r"no_such/param", P("fsdp")),
    ])
    assert find_dead_rules(rules, params) == [r"no_such/param"]
    shard_params(params, dp_mesh, rules)
    events = [
        json.loads(line) for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    dead = [e for e in events if e.get("event") == "dead_sharding_rules"]
    assert dead and dead[0]["patterns"] == [r"no_such/param"]


def test_memory_audit_strict_flag():
    from distributed_llms_example_tpu.utils.memory_audit import main as audit_main

    args = [
        "--model", "llama-2-7b", "--mesh", "fsdp=8", "--batch", "8",
        "--remat", "--grad-accum-steps", "8", "--analytic",
    ]
    # optimistic bound fits on one v5e-8 host...
    assert audit_main(args) == 0
    # ...but the conservative gradient-liveness bound does not: --strict
    # makes that CI-visible
    assert audit_main(args + ["--strict"]) == 1


def test_repo_lint_clean_and_catches_violations(tmp_path):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    # the repo itself is clean (this IS the CI check)
    assert repo_lint.main([]) == 0

    # a hot-path sync is caught — device_get by BOTH rule 1 (hot-path
    # sync) and rule 4 (step-cadence conversion; train/step.py is in
    # STEP_CADENCE_FILES), block_until_ready by rule 1
    bad_step = tmp_path / "step.py"
    bad_step.write_text("import jax\nx = jax.device_get(y)\nz = y.block_until_ready()\n")
    rel = os.path.join("distributed_llms_example_tpu", "train", "step.py")
    assert len(repo_lint.lint_file(str(bad_step), rel)) == 3

    # a bare axis-name spec outside parallel/ is caught, tuples included
    bad_spec = tmp_path / "rogue.py"
    bad_spec.write_text(
        "from jax.sharding import PartitionSpec as P\ns = P(('data', 'fsdp'), None)\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "models", "rogue.py")
    assert len(repo_lint.lint_file(str(bad_spec), rel)) == 1
    # ...but the same spec inside parallel/ is the sharding layer's job
    rel = os.path.join("distributed_llms_example_tpu", "parallel", "rogue.py")
    assert repo_lint.lint_file(str(bad_spec), rel) == []

    # rule 5: raw dropout primitives in models//train/ bypass the shared
    # fused helper (ops/fused_dropout.py) — aliased spellings included
    bad_drop = tmp_path / "dropmodel.py"
    bad_drop.write_text(
        "import flax.linen as nn\nimport jax\n"
        "from flax import linen\nfrom jax import random\n"
        "d = nn.Dropout(0.1)\n"
        "d2 = linen.Dropout(0.1)\n"
        "d3 = Dropout(0.1)\n"  # bare name NOT from the helper
        "m = jax.random.bernoulli(key, 0.9, (4, 4))\n"
        "m2 = random.bernoulli(key, 0.9, (4, 4))\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "models", "dropmodel.py")
    assert len(repo_lint.lint_file(str(bad_drop), rel)) == 5
    rel = os.path.join("distributed_llms_example_tpu", "train", "dropmodel.py")
    assert len(repo_lint.lint_file(str(bad_drop), rel)) == 5
    # ...the ops/ layer IS the implementation (helper + attention reference)
    rel = os.path.join("distributed_llms_example_tpu", "ops", "dropmodel.py")
    assert repo_lint.lint_file(str(bad_drop), rel) == []
    # the helper's OWN class, imported from ops.fused_dropout, is the
    # sanctioned spelling
    ok_drop = tmp_path / "okmodel.py"
    ok_drop.write_text(
        "from distributed_llms_example_tpu.ops.fused_dropout import Dropout\n"
        "d = Dropout(0.1)\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "models", "okmodel.py")
    assert repo_lint.lint_file(str(ok_drop), rel) == []

    # rule 12: time.sleep inside an except handler is an ad-hoc retry
    # loop — any spelling (time.sleep, aliased sleep, bare sleep)
    bad_retry = tmp_path / "retry.py"
    bad_retry.write_text(
        "import time\nfrom time import sleep\n"
        "try:\n    f()\nexcept OSError:\n"
        "    time.sleep(1)\n    sleep(2)\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "io", "retry.py")
    assert len(repo_lint.lint_file(str(bad_retry), rel)) == 2
    # ...the designated backoff helper is the owner; and a sleep OUTSIDE
    # an except handler (a poll cadence, not a retry) stays legal
    rel = os.path.join("distributed_llms_example_tpu", "utils", "backoff.py")
    assert repo_lint.lint_file(str(bad_retry), rel) == []
    ok_poll = tmp_path / "poll.py"
    ok_poll.write_text("import time\nwhile x:\n    time.sleep(0.1)\n")
    rel = os.path.join("distributed_llms_example_tpu", "obs", "poll.py")
    assert repo_lint.lint_file(str(ok_poll), rel) == []
    # the sanctioned call site: sleep_backoff in an except handler
    ok_retry = tmp_path / "okretry.py"
    ok_retry.write_text(
        "from distributed_llms_example_tpu.utils.backoff import sleep_backoff\n"
        "try:\n    f()\nexcept OSError:\n    d = sleep_backoff(d, cap_s=2.0)\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "io", "okretry.py")
    assert repo_lint.lint_file(str(ok_retry), rel) == []

    # rule 14: inline percentile/quantile computation outside the one
    # owner — numpy spellings and the sorted-index rank idiom both fork
    # the quantile definition the tail-latency gates compare against
    bad_pct = tmp_path / "pct.py"
    bad_pct.write_text(
        "import numpy as np\n"
        "p = np.percentile(xs, 99)\n"
        "q = np.quantile(xs, 0.99)\n"
        "r = sorted(xs)[int(0.99 * (len(xs) - 1))]\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "serving", "pct.py")
    assert len(repo_lint.lint_file(str(bad_pct), rel)) == 3
    # ...the owner holds the one definition
    rel = os.path.join("distributed_llms_example_tpu", "obs", "spans.py")
    assert repo_lint.lint_file(str(bad_pct), rel) == []
    # the sanctioned spelling, and a plain sorted()[0] (min, not a
    # quantile), stay legal everywhere
    ok_pct = tmp_path / "okpct.py"
    ok_pct.write_text(
        "from distributed_llms_example_tpu.obs.spans import percentiles\n"
        "(p99,) = percentiles(xs, (0.99,))\n"
        "first = sorted(xs)[0]\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "serving", "okpct.py")
    assert repo_lint.lint_file(str(ok_pct), rel) == []

    # rule 15: raw memory_stats()/live_buffers() reads outside the memory
    # owners fork the HBM account (no absent-beats-zero, no watermark
    # delta semantics) — any qualifier spelling
    bad_mem = tmp_path / "mem.py"
    bad_mem.write_text(
        "import jax\n"
        "for d in jax.local_devices():\n"
        "    s = d.memory_stats()\n"
        "b = jax.local_devices()[0].live_buffers()\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "serving", "mem.py")
    assert len(repo_lint.lint_file(str(bad_mem), rel)) == 2
    # ...both owners hold the raw reads
    rel = os.path.join("distributed_llms_example_tpu", "obs", "memprof.py")
    assert repo_lint.lint_file(str(bad_mem), rel) == []
    rel = os.path.join("distributed_llms_example_tpu", "utils", "memory_audit.py")
    assert repo_lint.lint_file(str(bad_mem), rel) == []
    # the sanctioned read path stays legal everywhere
    ok_mem = tmp_path / "okmem.py"
    ok_mem.write_text(
        "from distributed_llms_example_tpu.obs import memprof\n"
        "stats = memprof.hbm_stats()\n"
        "wm = memprof.Watermark()\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "serving", "okmem.py")
    assert repo_lint.lint_file(str(ok_mem), rel) == []

    # rule 16: the block-identity ledger is cache_pool.py's alone — a
    # refcount poked from outside the owner breaks the refcount ==
    # live-references invariant, and a second hashlib-based block hash
    # in serving/ forks the chained content identity
    bad_px = tmp_path / "px.py"
    bad_px.write_text(
        "import hashlib\n"
        "from hashlib import sha256\n"
        "pool._ref[b] -= 1\n"
        "h = pool._hash_of.get(b)\n"
        "blk = pool._index[h]\n"
        "pool._lru.pop(b, None)\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "serving", "px.py")
    assert len(repo_lint.lint_file(str(bad_px), rel)) == 6
    # ...the owner holds the ledger and the hash
    rel = os.path.join("distributed_llms_example_tpu", "serving", "cache_pool.py")
    assert repo_lint.lint_file(str(bad_px), rel) == []
    # hashlib outside serving/ is fine (checkpoint digests etc.); the
    # ledger attrs stay forbidden repo-wide
    bad_ref = tmp_path / "ref.py"
    bad_ref.write_text("import hashlib\npool._ref[b] += 1\n")
    rel = os.path.join("distributed_llms_example_tpu", "io", "ref.py")
    assert len(repo_lint.lint_file(str(bad_ref), rel)) == 1
    # the sanctioned API stays legal everywhere in serving/
    ok_px = tmp_path / "okpx.py"
    ok_px.write_text(
        "from distributed_llms_example_tpu.serving import cache_pool\n"
        "hashes = cache_pool.chain_hashes(toks, 8)\n"
        "chain = pool.match_chain(hashes)\n"
        "pool.acquire(chain)\n"
        "pool.free(chain)\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "serving", "okpx.py")
    assert repo_lint.lint_file(str(ok_px), rel) == []


# ---------------------------------------------------------------------------
# grad accumulation (ISSUE 5): accumulator-mirror spec lint, the
# once-per-step placement census, the ppermute-chain smell, rule 5a
# ---------------------------------------------------------------------------


def test_spec_lint_accumulator_mirror_clean_and_catches_drift(monkeypatch):
    """The fp32 accumulators must mirror the param specs leaf for leaf:
    the live accumulator_shardings is the identity (clean), and an edit
    that replicates the accumulators is an error naming the leaf."""
    import distributed_llms_example_tpu.train.step as step_mod
    from distributed_llms_example_tpu.analysis.spec_lint import lint_accumulator_mirror

    a_params = _abstract_llama_params()
    assert lint_accumulator_mirror(a_params) == []

    # a drifted implementation: replicate every accumulator leaf
    monkeypatch.setattr(
        step_mod, "accumulator_shardings",
        lambda tree: jax.tree.map(lambda s: P(), tree),
    )
    findings = lint_accumulator_mirror(a_params)
    assert findings and all(f.severity == "error" for f in findings)
    assert {f.code for f in findings} == {"accumulator-spec-mismatch"}
    # only the genuinely sharded leaves drifted (replicated ones still match)
    assert any("kernel" in f.message for f in findings)


def test_ir_once_per_step_placement_fixture():
    """Hand-built HLO: the census attributes span-stamped instructions to
    their computation, and the finding fires iff optimizer code sits in a
    while-body (or warns when the metadata is missing entirely)."""
    from distributed_llms_example_tpu.analysis.ir_lint import (
        once_per_step_finding,
        once_per_step_placement,
    )
    from distributed_llms_example_tpu.train.step import once_per_step_source_spans

    spans = once_per_step_source_spans()
    f, first, _last = spans[0]
    meta = f'metadata={{op_name="adamw" source_file="{f}" source_line={first}}}'

    def prog(opt_in_body: bool) -> str:
        body_extra = f"\n  %opt.b = f32[] add(f32[] %g.1, f32[] %g.1), {meta}" if opt_in_body else ""
        entry_extra = "" if opt_in_body else f"\n  %opt.e = f32[] add(f32[] %c.1, f32[] %c.1), {meta}"
        return f"""HloModule fixture

%body.1 (p.1: (s32[], f32[])) -> (s32[], f32[]) {{
  %p.1 = (s32[], f32[]) parameter(0)
  %i.1 = s32[] get-tuple-element((s32[], f32[]) %p.1), index=0
  %g.1 = f32[] get-tuple-element((s32[], f32[]) %p.1), index=1{body_extra}
  ROOT %t.1 = (s32[], f32[]) tuple(%i.1, %g.1)
}}

%cond.1 (q.1: (s32[], f32[])) -> pred[] {{
  %q.1 = (s32[], f32[]) parameter(0)
  ROOT %lt.1 = pred[] compare(s32[] %j.1, s32[] %n.1), direction=LT
}}

ENTRY %main.1 (a.1: f32[]) -> f32[] {{
  %c.1 = f32[] parameter(0)
  %init.1 = (s32[], f32[]) tuple(s32[] %z.1, f32[] %c.1)
  %w.1 = (s32[], f32[]) while((s32[], f32[]) %init.1), condition=%cond.1, body=%body.1{entry_extra}
  ROOT %r.1 = f32[] get-tuple-element((s32[], f32[]) %w.1), index=1
}}
"""

    good = prog(opt_in_body=False)
    census = once_per_step_placement(good, spans)
    assert census == {"total": 1, "in_loop": 0, "in_loop_examples": []}
    assert once_per_step_finding(good, spans) is None

    bad = prog(opt_in_body=True)
    census = once_per_step_placement(bad, spans)
    assert census["total"] == 1 and census["in_loop"] == 1
    finding = once_per_step_finding(bad, spans)
    assert finding is not None and finding.severity == "error"
    assert finding.code == "optimizer-in-scan-body"

    # no span-stamped instruction at all: the census proves nothing → warning
    empty = prog(opt_in_body=False).replace(meta, "")
    finding = once_per_step_finding(empty, spans)
    assert finding is not None and finding.severity == "warning"
    assert finding.code == "optimizer-census-empty"


_PPERMUTE_CHAIN_HLO = """\
HloModule rings

ENTRY %main {
  %p0 = f32[64]{0} parameter(0)
  %cp.1 = f32[64]{0} collective-permute(f32[64]{0} %p0), source_target_pairs={{0,1},{1,0}}
  %cp.2 = f32[64]{0} collective-permute(f32[64]{0} %cp.1), source_target_pairs={{0,1},{1,0}}
  %cp.3 = f32[64]{0} collective-permute(f32[64]{0} %cp.2), source_target_pairs={{0,1},{1,0}}
  ROOT %t.1 = f32[64]{0} tuple(%cp.3)
}
"""


def test_ir_ppermute_chain_smell_fixture():
    """The ROADMAP smell, pinned on a hand-built 3-permute dependency
    chain: longer than the stage ring → warning with the chain length;
    within the ring, or no stage axis → silent."""
    from distributed_llms_example_tpu.analysis.ir_lint import (
        parse_hlo_instructions,
        ppermute_chain_smell,
    )

    instrs = parse_hlo_instructions(_PPERMUTE_CHAIN_HLO)
    smell = ppermute_chain_smell(instrs, {"stage": 2})
    assert smell is not None and smell.severity == "warning"
    assert smell.code == "ppermute-chain-exceeds-stage-ring"
    assert smell.context == {"chain_length": 3, "stage": 2}
    # a 3-hop chain fits a 4-stage ring; stage=1 has no ring at all
    assert ppermute_chain_smell(instrs, {"stage": 4}) is None
    assert ppermute_chain_smell(instrs, {"stage": 1, "data": 8}) is None
    # mixed stage x sequence: ring/context-parallel permutes chain once
    # per layer and are textually indistinguishable — the smell stands down
    assert ppermute_chain_smell(instrs, {"stage": 2, "sequence": 2}) is None
    # wired into the scanner (stage>1 meshes only)
    findings = scan_hlo_text(_PPERMUTE_CHAIN_HLO, mesh_axes={"stage": 2, "data": 2})
    assert "ppermute-chain-exceeds-stage-ring" in _codes(findings)
    findings = scan_hlo_text(_PPERMUTE_CHAIN_HLO, mesh_axes={"data": 8})
    assert "ppermute-chain-exceeds-stage-ring" not in _codes(findings)


def test_cli_grad_accum_pipelined_composition(capsys):
    """--grad-accum-steps > 1 on a stage>1 mesh is condemned by the
    composition table before any compile."""
    rc, findings = _run_cli(
        capsys, "--model", "llama-test", "--mesh", "stage=2,data=2",
        "--grad-accum-steps", "2", "--no-ir",
    )
    assert rc == 1
    assert any(f.get("code") == "grad-accum-pipelined" for f in findings)


def test_repo_lint_grad_accum_rule(tmp_path):
    """Rule 5a: a manual gradient accumulator outside train/step.py is a
    rogue second accumulation layer — flagged in models/ and train/,
    exempt in the owning file and in parallel/ (the pipeline executors'
    schedule-internal microbatching)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "acc.py"
    bad.write_text(
        "import jax\n"
        "from jax.tree_util import tree_map\n"
        "def f(acc, grads, loss, x):\n"
        "    acc += grads\n"
        "    acc = jax.tree.map(lambda a, g: a + g, acc, grads)\n"
        "    acc = tree_map(lambda a, g: a + g, acc, grads)\n"  # bare-name import must not evade
        "    loss += x\n"  # non-gradient accumulator stays legal
        "    return acc, loss\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "models", "acc.py")
    assert len(repo_lint.lint_file(str(bad), rel)) == 3
    rel = os.path.join("distributed_llms_example_tpu", "train", "acc.py")
    assert len(repo_lint.lint_file(str(bad), rel)) == 3
    # the owner is exempt — train/step.py IS the accumulation layer
    rel = os.path.join("distributed_llms_example_tpu", "train", "step.py")
    assert repo_lint.lint_file(str(bad), rel) == []
    # parallel/ owns the pipeline executors' microbatching
    rel = os.path.join("distributed_llms_example_tpu", "parallel", "acc.py")
    assert repo_lint.lint_file(str(bad), rel) == []


def test_repo_lint_grad_collective_rule(tmp_path):
    """Rule 9 (ISSUE 12): a raw lax.psum / psum_scatter / all_to_all over
    a gradient tree — or a manual int8 cast of gradients — outside
    train/step.py bypasses the --grad-compression dispatch
    (ops/quant_collectives.py: error feedback, shared-scale int-safe
    wire, off-path bit-identity pin)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "qc.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def f(grads, x, axis):\n"
        "    g = lax.psum(grads, axis)\n"
        "    g2 = lax.psum_scatter(grads, axis)\n"
        "    g3 = jax.lax.all_to_all(grads, axis, 0, 0)\n"
        "    q = grads.astype(jnp.int8)\n"
        "    q2 = grads.astype(dtype=jnp.int8)\n"  # kwarg form must not evade
        "    ok = lax.psum(x, axis)\n"  # non-gradient collectives stay legal
        "    ok2 = x.astype(jnp.int8)\n"  # non-gradient int8 casts too
        "    return g, g2, g3, q, ok, ok2\n"
    )
    # Under models/ rule 10 (KV-cast ownership, ISSUE 13) also fires on
    # every astype(int8) — including the non-gradient one — on top of
    # rule 9's five hits; under train/ only rule 9 applies.
    for d, expected in (("models", 8), ("train", 5)):
        rel = os.path.join("distributed_llms_example_tpu", d, "qc.py")
        violations = repo_lint.lint_file(str(bad), rel)
        assert len(violations) == expected, violations
        assert any("quant_collectives" in v for v in violations)
    # the owners are exempt: train/step.py calls the compression layer,
    # ops/ and parallel/ ARE implementation layers
    rel = os.path.join("distributed_llms_example_tpu", "train", "step.py")
    assert repo_lint.lint_file(str(bad), rel) == []
    rel = os.path.join("distributed_llms_example_tpu", "ops", "qc.py")
    assert repo_lint.lint_file(str(bad), rel) == []


def test_repo_lint_kv_cast_rule(tmp_path):
    """Rule 10 (ISSUE 13): a raw ``.astype(int8/uint8)`` in models/,
    serving/, evaluation/ or ops/mha.py forks the KV-cache number format
    away from the quantize_kv/dequantize_kv scale contract; the owners
    (ops/flash_attention.py, serving/cache_pool.py) stay exempt, and
    int8 *allocation* (jnp.zeros) stays legal everywhere."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "kv.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def f(k, v):\n"
        "    qk = k.astype(jnp.int8)\n"
        "    qv = v.astype(dtype=jnp.uint8)\n"  # kwarg + uint8 must not evade
        "    pool = jnp.zeros((4, 8), jnp.int8)\n"  # allocation stays legal
        "    wide = k.astype(jnp.float32)\n"  # non-int8 casts stay legal
        "    return qk, qv, pool, wide\n"
    )
    for d in ("models", "serving", "evaluation"):
        rel = os.path.join("distributed_llms_example_tpu", d, "kv.py")
        violations = repo_lint.lint_file(str(bad), rel)
        assert len(violations) == 2, violations
        assert all("quantize_kv" in v for v in violations)
    # the cache-write site is covered by file, not dir
    rel = os.path.join("distributed_llms_example_tpu", "ops", "mha.py")
    assert len(repo_lint.lint_file(str(bad), rel)) == 2
    # the owners are exempt; so is everything outside the covered dirs
    for rel in (
        os.path.join("distributed_llms_example_tpu", "ops", "flash_attention.py"),
        os.path.join("distributed_llms_example_tpu", "serving", "cache_pool.py"),
        os.path.join("distributed_llms_example_tpu", "train", "kv.py"),
    ):
        assert repo_lint.lint_file(str(bad), rel) == []


def test_repo_lint_mesh_ownership_rule(tmp_path):
    """Rule 11 (ISSUE 14): raw ``Mesh(...)`` construction and any
    ``jax.distributed.*`` call outside core/mesh.py fork the distributed
    lifecycle the topology-change path owns (teardown ordering, the
    topology-aware device order, the gloo-on-CPU flag); ``AbstractMesh``
    (shape-only, no devices) stays legal, and the owner is exempt."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "m.py"
    bad.write_text(
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh, AbstractMesh\n"
        "def f(devs):\n"
        "    m = Mesh(np.array(devs).reshape(2, 4), ('data', 'fsdp'))\n"
        "    m2 = jax.sharding.Mesh(devs, ('data',))\n"
        "    jax.distributed.initialize('c:1', 2, 0)\n"
        "    jax.distributed.shutdown()\n"
        "    ok = AbstractMesh((2,), ('data',))\n"  # shape-only: legal
        "    return m, m2, ok\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "train", "m.py")
    violations = repo_lint.lint_file(str(bad), rel)
    assert len(violations) == 4, violations
    assert any("build_mesh" in v for v in violations)
    assert any("reinitialize_distributed" in v for v in violations)
    # the owner is exempt
    rel = os.path.join("distributed_llms_example_tpu", "core", "mesh.py")
    assert repo_lint.lint_file(str(bad), rel) == []


def test_repo_lint_ckpt_manager_rule(tmp_path):
    """Rule 6 (ISSUE 6): bare orbax ``manager.save``/``manager.restore``
    outside io/checkpoint.py bypasses the integrity wrappers (save
    retry/backoff, checksum manifest, verify-before-restore with
    fallback) — flagged everywhere except the owning module."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "rogue_ckpt.py"
    bad.write_text(
        "def f(self, manager, ckpt_manager, state, step):\n"
        "    manager.save(step, state)\n"
        "    manager.restore(step)\n"
        "    self.manager.save(step, state)\n"       # attribute base too
        "    ckpt_manager.restore(step)\n"           # aliased spelling
        "    self.checkpointer.save(step, state)\n"  # the WRAPPER is legal
        "    manager.wait_until_finished()\n"  # non-save/restore call is ok
    )
    rel = os.path.join("distributed_llms_example_tpu", "train", "rogue_ckpt.py")
    violations = repo_lint.lint_file(str(bad), rel)
    assert len(violations) == 4
    assert all("verified checkpoint wrappers" in v for v in violations)
    # the owning module holds the one sanctioned call site
    rel = os.path.join("distributed_llms_example_tpu", "io", "checkpoint.py")
    assert repo_lint.lint_file(str(bad), rel) == []


def test_repo_lint_chrome_trace_rule(tmp_path):
    """Rule 7 (ISSUE 9): Chrome-trace event dicts (``"ph"``+``"ts"``
    keys, or a ``"traceEvents"`` container) may only be built in
    obs/trace.py — a second trace producer means a second clock epoch
    and no cross-rank step alignment (the trace twin of the sink-bypass
    rule)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "rogue_trace.py"
    bad.write_text(
        "import json\n"
        "ev = {'name': 'x', 'ph': 'X', 'ts': 12.5, 'dur': 3.0}\n"
        "doc = {'traceEvents': [ev]}\n"
        "ok = {'ph': 'X'}\n"              # ph alone is not a trace event
        "ok2 = {'ts': 1.0, 'dur': 2.0}\n"  # ts without ph neither
    )
    for layer in ("models", "train", "obs", "serving"):
        rel = os.path.join("distributed_llms_example_tpu", layer, "rogue_trace.py")
        violations = repo_lint.lint_file(str(bad), rel)
        assert len(violations) == 2, (layer, violations)
        assert all("obs/trace.py" in v for v in violations)
    # the exporter itself IS the owner
    rel = os.path.join("distributed_llms_example_tpu", "obs", "trace.py")
    assert repo_lint.lint_file(str(bad), rel) == []
    # and the repo stays clean under the new rule
    assert repo_lint.main([]) == 0


# ---------------------------------------------------------------------------
# fused optimizer apply (ISSUE 10): the moment-mirror spec lint, the
# fp32-param-copy census extension, repo-lint rule 8
# ---------------------------------------------------------------------------


def test_spec_lint_optimizer_moment_mirror_clean_and_catches_anchor():
    """The adam moments resolve to the param specs under the stock rules
    (their paths END with the param path and the regexes are unanchored);
    an anchored rule that matches the param but not its moment path is
    exactly the drift this pass exists to catch."""
    from distributed_llms_example_tpu.analysis.spec_lint import (
        lint_optimizer_moment_mirror,
    )
    from distributed_llms_example_tpu.parallel.sharding import ShardingRules

    a_params = _abstract_llama_params()
    assert lint_optimizer_moment_mirror(a_params) == []

    anchored = ShardingRules(rules=[(r"^block_0/self_attn", P("fsdp", "tensor"))])
    findings = lint_optimizer_moment_mirror(a_params, anchored)
    assert findings and all(f.severity == "error" for f in findings)
    assert {f.code for f in findings} == {"optimizer-moment-spec-mismatch"}
    assert any("mu" in f.message for f in findings)


def test_ir_census_counts_fp32_param_copies():
    """The in-place contract extension: span-attributed f32 copy
    instructions whose element count matches a param leaf are counted
    (and the finding fires) only when param_elems is supplied — the
    legacy census dict shape is untouched otherwise."""
    from distributed_llms_example_tpu.analysis.ir_lint import (
        in_place_apply_finding,
        once_per_step_placement,
    )
    from distributed_llms_example_tpu.train.step import once_per_step_source_spans

    spans = once_per_step_source_spans()
    f, first, _last = spans[0]
    meta = f'metadata={{op_name="adamw" source_file="{f}" source_line={first}}}'
    text = f"""HloModule fixture

ENTRY %main.1 (a.1: f32[128]) -> f32[128] {{
  %c.1 = f32[128]{{0}} parameter(0)
  %cp.1 = f32[128]{{0}} copy(f32[128]{{0}} %c.1), {meta}
  %cp.2 = f32[64]{{0}} copy(f32[64]{{0}} %c.1), {meta}
  %cp.3 = s32[128]{{0}} copy(s32[128]{{0}} %c.1), {meta}
  %cp.4 = f32[128]{{0}} copy(f32[128]{{0}} %c.1)
  %cp.5 = (f32[128]{{0}}, f32[128]{{0}}, u32[]) copy-start(f32[128]{{0}} %c.1), {meta}
  ROOT %r.1 = f32[128]{{0}} add(f32[128]{{0}} %cp.1, f32[128]{{0}} %cp.1), {meta}
}}
"""
    # legacy shape: no param_elems, no copy keys
    census = once_per_step_placement(text, spans)
    assert census == {"total": 5, "in_loop": 0, "in_loop_examples": []}
    # with param elems: the f32[128] span-attributed copies count — incl.
    # the ASYNC copy-start tuple form (its largest tuple element is the
    # copied buffer); the wrong-size (64), wrong-dtype (s32), and
    # unattributed copies do not
    census = once_per_step_placement(
        text, spans, param_elems=[128], min_copy_elems=0
    )
    assert census["fp32_param_copies"] == 2
    assert census["fp32_copy_examples"] == ["main.1:%cp.1", "main.1:%cp.5"]
    finding = in_place_apply_finding(text, spans, [128], min_copy_elems=0)
    assert finding is not None and finding.severity == "warning"
    assert finding.code == "optimizer-param-copy"
    # no matching copies → no finding
    assert in_place_apply_finding(text, spans, [999], min_copy_elems=0) is None
    # the default floor excludes small layout-normalization relayouts:
    # the same program is clean without the explicit floor override
    assert in_place_apply_finding(text, spans, [128]) is None


def test_repo_lint_optim_apply_rule(tmp_path):
    """Rule 8: raw apply_updates / manual p - lr*u tree-maps are
    forbidden in models/ and train/ outside train/optim.py (the
    --optim-impl dispatch owner)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    bad = tmp_path / "rogue_optim.py"
    bad.write_text(
        "import jax, optax\n"
        "def apply(params, updates, lr, learning_rate):\n"
        "    p1 = optax.apply_updates(params, updates)\n"          # 1
        "    p2 = apply_updates(params, updates)\n"                # 2
        "    p3 = jax.tree.map(lambda p, u: p - lr * u, params, updates)\n"  # 3
        "    p4 = jax.tree_util.tree_map(\n"                       # 4
        "        lambda p, u: p + (-learning_rate) * u, params, updates)\n"
        "    ok = jax.tree.map(lambda a, b: a + b, params, updates)\n"
        "    return p1, p2, p3, p4, ok\n"
    )
    for layer in ("models", "train"):
        rel = os.path.join("distributed_llms_example_tpu", layer, "rogue_optim.py")
        violations = repo_lint.lint_file(str(bad), rel)
        assert len(violations) == 4, (layer, violations)
        assert sum("apply_updates" in v for v in violations) == 2
        assert sum("p - lr*u" in v for v in violations) == 2
    # train/optim.py owns the apply; other layers are out of scope
    rel = os.path.join("distributed_llms_example_tpu", "train", "optim.py")
    assert repo_lint.lint_file(str(bad), rel) == []
    rel = os.path.join("distributed_llms_example_tpu", "serving", "rogue_optim.py")
    assert repo_lint.lint_file(str(bad), rel) == []
    # and the live tree stays clean under the new rule
    assert repo_lint.main([]) == 0


# ---------------------------------------------------------------------------
# bench_diff (ISSUE 11 satellite): round-over-round regression gate
# ---------------------------------------------------------------------------


def _load_bench_diff():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_diff",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "bench_diff.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_diff_directions_and_thresholds():
    bench_diff = _load_bench_diff()
    old = {
        "trainer_loop": {
            "tokens_per_sec_chip_prefetch2": 100.0,
            "dispatch_efficiency": 0.95,
            "device_account": {"buckets_ms": {"attn": 10.0}},
        },
        "serve": {"ttft_p95_ms": 200.0, "slo_attainment": 0.9},
        "chips": 8,
    }
    new = {
        "trainer_loop": {
            "tokens_per_sec_chip_prefetch2": 90.0,   # -10%: regression
            "dispatch_efficiency": 0.96,             # improvement
            "device_account": {"buckets_ms": {"attn": 11.0}},  # +10% device ms
        },
        "serve": {"ttft_p95_ms": 212.0, "slo_attainment": 0.9},  # +6% ttft
        "chips": 8,
    }
    rows = {r["field"]: r for r in bench_diff.compare(old, new)}
    assert rows["trainer_loop.tokens_per_sec_chip_prefetch2"]["verdict"] == "regressed"
    assert rows["trainer_loop.dispatch_efficiency"]["verdict"] == "ok"  # +1% < 5%
    assert rows["serve.ttft_p95_ms"]["verdict"] == "regressed"  # lower-better
    assert rows["serve.slo_attainment"]["verdict"] == "ok"
    assert rows["chips"]["verdict"] == "info"  # no direction: never gates
    assert rows["trainer_loop.device_account.buckets_ms.attn"]["verdict"] == "regressed"
    # per-field threshold override silences the ttft wiggle (leaf name)
    rows2 = {
        r["field"]: r
        for r in bench_diff.compare(old, new, overrides={"ttft_p95_ms": 0.10})
    }
    assert rows2["serve.ttft_p95_ms"]["verdict"] == "ok"
    # full-dot-path override beats the leaf override
    rows3 = {
        r["field"]: r
        for r in bench_diff.compare(
            old, new,
            overrides={"ttft_p95_ms": 0.10, "serve.ttft_p95_ms": 0.01},
        )
    }
    assert rows3["serve.ttft_p95_ms"]["verdict"] == "regressed"


def test_bench_diff_cli_exit_codes_and_markdown(tmp_path, capsys):
    import json as _json

    bench_diff = _load_bench_diff()
    a = tmp_path / "BENCH_a.json"
    b = tmp_path / "BENCH_b.json"
    a.write_text(_json.dumps({"tps": {"tokens_per_sec_chip": 100.0}, "n": 3}))
    # a clean round: tiny wiggle under the default 5% threshold
    b.write_text(_json.dumps({"tps": {"tokens_per_sec_chip": 98.0}, "n": 3}))
    md_path = tmp_path / "delta.md"
    assert bench_diff.main([str(a), str(b), "--markdown-out", str(md_path)]) == 0
    md = md_path.read_text()
    assert "bench diff" in md and "tokens_per_sec_chip" in md
    capsys.readouterr()
    # a regressed round exits nonzero (the CI contract) and names the field
    b.write_text(_json.dumps({"tps": {"tokens_per_sec_chip": 80.0}, "n": 3}))
    assert bench_diff.main([str(a), str(b)]) == 1
    err = capsys.readouterr().err
    assert "REGRESSED tps.tokens_per_sec_chip" in err
    # loosening the threshold for that field greens it
    assert bench_diff.main([
        str(a), str(b), "--threshold", "tokens_per_sec_chip=0.5",
    ]) == 0
    capsys.readouterr()
    # disjoint artifacts: no shared numeric fields is its own error
    c = tmp_path / "BENCH_c.json"
    c.write_text(_json.dumps({"other": {"x": "y"}}))
    assert bench_diff.main([str(a), str(c)]) == 2
    capsys.readouterr()


def test_bench_diff_markdown_orders_regressions_first(tmp_path):
    bench_diff = _load_bench_diff()
    rows = bench_diff.compare(
        {"a_ms": 100.0, "tokens_per_sec": 10.0, "count": 1},
        {"a_ms": 150.0, "tokens_per_sec": 20.0, "count": 1},
    )
    md = bench_diff.render_markdown(rows, "old.json", "new.json")
    lines = [ln for ln in md.splitlines() if ln.startswith("| ")]
    # header row, then the regression, then the improvement, then info
    assert "a_ms" in lines[1] and "REGRESSED" in lines[1]
    assert "tokens_per_sec" in lines[2] and "improved" in lines[2]
    assert "count" in lines[3]


def test_repo_lint_rule7_covers_devprof(tmp_path):
    """Rule 7 (trace-dict ownership) guards the NEW device-attribution
    module: obs/devprof.py PARSES trace events but must never BUILD them
    — a second producer would mean a second clock epoch with no
    cross-rank step alignment.  The shipped module is clean; a rogue
    version that constructs a Chrome-trace dict trips the lint."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    root = os.path.join(os.path.dirname(__file__), "..")
    rel = os.path.join("distributed_llms_example_tpu", "obs", "devprof.py")
    assert repo_lint.lint_file(os.path.join(root, rel), rel) == []
    rogue = tmp_path / "devprof.py"
    rogue.write_text(
        "def export(events):\n"
        "    return [{'ph': 'X', 'ts': 1.0, 'dur': 2.0, 'name': n}\n"
        "            for n in events]\n"
    )
    violations = repo_lint.lint_file(str(rogue), rel)
    assert len(violations) == 1 and "obs/trace.py" in violations[0]
    # ...while the owner itself is allowed to build them
    rel_owner = os.path.join("distributed_llms_example_tpu", "obs", "trace.py")
    assert repo_lint.lint_file(str(rogue), rel_owner) == []


def test_repo_lint_rank_conditional_rule(tmp_path):
    """Rule 13 (ISSUE 16): a bare ``process_index()``/``process_count()``
    conditional outside the rank-branching owners is forbidden — raw rank
    identity feeding a branch is the pod-deadlock seed the divergence
    pass hunts semantically; this is the cheap lexical backstop."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    # (the annotated repo being clean is already pinned by
    # test_repo_lint_clean_and_catches_violations's main([]) run — rule 13
    # rides the same driver, so a whole-tree re-lint here is pure wall)

    bad = tmp_path / "rogue.py"
    bad.write_text(
        "import jax\n"
        "if jax.process_index() == 0:\n"
        "    save()\n"
        "while jax.process_count() > 1:\n"
        "    sync()\n"
        "x = 1 if jax.process_index() else 0\n"
        "assert jax.process_count() == 8\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "train", "rogue.py")
    violations = repo_lint.lint_file(str(bad), rel)
    assert len(violations) == 4
    assert all("pod-agreed" in v for v in violations)

    # ...every whitelisted owner keeps its rank-branching license
    for owner in sorted(repo_lint.RANK_CONDITIONAL_OWNERS):
        assert repo_lint.lint_file(str(bad), owner) == []

    # a NON-conditional use (gating nothing) is not rule 13's business
    ok_use = tmp_path / "use.py"
    ok_use.write_text("import jax\npid = jax.process_index()\n")
    assert repo_lint.lint_file(str(ok_use), rel) == []

    # the pragma waives, on either the statement or the call line
    waived = tmp_path / "waived.py"
    waived.write_text(
        "import jax\n"
        "if jax.process_count() == 1:  # pod-agreed: pod-uniform fast path\n"
        "    save()\n"
        "if (  # pod-agreed: pod-uniform guard\n"
        "    jax.process_count() > 1\n"
        "):\n"
        "    sync()\n"
    )
    assert repo_lint.lint_file(str(waived), rel) == []


def test_bench_diff_config_knobs_never_gate():
    """SLO settings and thresholds are config stamped into the artifact,
    not measurements — changing them between rounds must read as info,
    not regression (ttft_slo_ms matches both 'ttft' and '_ms' needles)."""
    bench_diff = _load_bench_diff()
    rows = {
        r["field"]: r
        for r in bench_diff.compare(
            {"serve": {"ttft_slo_ms": 500.0, "ttft_p95_ms": 100.0}},
            {"serve": {"ttft_slo_ms": 250.0, "ttft_p95_ms": 100.0}},
        )
    }
    assert rows["serve.ttft_slo_ms"]["verdict"] == "info"
    assert rows["serve.ttft_p95_ms"]["verdict"] == "ok"
