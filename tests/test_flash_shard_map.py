"""Flash attention under GSPMD: per-shard Pallas via shard_map.

VERDICT round-1 item 2: multi-chip training silently fell back to XLA
attention because an opaque pallas call can't be partitioned.  These tests
prove the shard_map wiring — the kernel runs per (data×fsdp, tensor) shard
on the 8-device mesh with forward+gradient parity against XLA attention —
and that ``attention_impl="auto"`` selects flash on TPU meshes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llms_example_tpu.models.llama import LlamaForCausalLM
from distributed_llms_example_tpu.models.registry import LLAMA_CONFIGS
from distributed_llms_example_tpu.ops.mha import select_attention_impl
from distributed_llms_example_tpu.parallel.activation import activation_mesh
from distributed_llms_example_tpu.parallel.sharding import batch_sharding


def test_flash_shard_map_parity_fwd_grad(mesh8):
    """llama-test on the 2x2x2 mesh: flash (per-shard, interpreted) must
    match XLA attention in both logits-loss and gradients."""
    cfg = LLAMA_CONFIGS["llama-test"]
    assert cfg.num_attention_heads % mesh8.shape["tensor"] == 0
    mods = {
        impl: LlamaForCausalLM(dataclasses.replace(cfg, attention_impl=impl))
        for impl in ("xla", "flash")
    }
    rng = np.random.RandomState(0)
    bsh = batch_sharding(mesh8)
    ids = jax.device_put(rng.randint(3, cfg.vocab_size, (8, 64)).astype(np.int32), bsh)
    mask = np.ones((8, 64), np.int32)
    mask[0, 50:] = 0
    mask = jax.device_put(mask, bsh)
    params = mods["xla"].init(jax.random.PRNGKey(0), ids, mask)["params"]

    results = {}
    for impl, m in mods.items():
        def f(p, m=m):
            logits = m.apply({"params": p}, ids, mask)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        with activation_mesh(mesh8):
            loss, grads = jax.jit(jax.value_and_grad(f))(params)
        results[impl] = (float(loss), jax.device_get(grads))

    l_x, g_x = results["xla"]
    l_f, g_f = results["flash"]
    np.testing.assert_allclose(l_x, l_f, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_x), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3)


def test_auto_selects_flash_on_tpu_mesh(mesh8):
    """The selection logic (pure function): auto → flash on a TPU mesh with
    even head/batch splits; xla whenever flash can't run."""
    base = dict(
        batch=8, heads=4, head_dim=16, q_len=256, kv_len=256,
        use_cache=False, mesh=mesh8, backend="tpu", device_count=8,
    )
    impl, reason = select_attention_impl("auto", **base)
    assert impl == "flash" and "shard_map" in reason

    # single chip: no mesh needed
    impl, _ = select_attention_impl("auto", **{**base, "mesh": None, "device_count": 1})
    assert impl == "flash"

    # CPU backend: interpreted kernel is pure overhead
    impl, _ = select_attention_impl("auto", **{**base, "backend": "cpu"})
    assert impl == "xla"

    # multi-device jit without a mesh context can't partition the kernel
    impl, _ = select_attention_impl("auto", **{**base, "mesh": None})
    assert impl == "xla"

    # heads don't split over tensor=2
    impl, _ = select_attention_impl("auto", **{**base, "heads": 3})
    assert impl == "xla"

    # batch doesn't split over data*fsdp=4
    impl, _ = select_attention_impl("auto", **{**base, "batch": 2})
    assert impl == "xla"

    # decode steps always take the cache path
    impl, _ = select_attention_impl("auto", **{**base, "use_cache": True})
    assert impl == "xla"

    # tiny score matrices aren't worth the kernel
    impl, _ = select_attention_impl("auto", **{**base, "q_len": 32, "kv_len": 32})
    assert impl == "xla"

    # forced flash overrides the backend heuristic (but not eligibility)
    impl, _ = select_attention_impl("flash", **{**base, "backend": "cpu"})
    assert impl == "flash"
    impl, _ = select_attention_impl("flash", **{**base, "backend": "cpu", "use_cache": True})
    assert impl == "xla"


def test_flash_shard_map_in_train_step(mesh8):
    """End to end: a full sharded train step with attention_impl='flash'
    produces the same loss/grad-norm as the XLA-attention step."""
    import optax

    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    rng = np.random.RandomState(3)
    batch = {
        "input_ids": rng.randint(3, 250, (8, 64)).astype(np.int32),
        "attention_mask": np.ones((8, 64), np.int32),
        "labels": rng.randint(3, 250, (8, 64)).astype(np.int32),
    }
    batch["labels"][:, :16] = -100
    tx = optax.sgd(1e-2)
    sched = lambda s: 1e-2  # noqa: E731

    metrics_by_impl = {}
    for impl in ("xla", "flash"):
        lm = load_model("llama-test", attention_impl=impl)
        params = jax.device_get(lm.init_params(0))
        build = make_train_step(
            lm.module, lm.config, tx, sched, mesh8, donate=False, is_seq2seq=False
        )
        state = create_train_state(shard_params(params, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        _, metrics = step(state, put_batch(batch, mesh8))
        metrics_by_impl[impl] = (float(metrics["loss"]), float(metrics["grad_norm"]))

    (l_x, g_x), (l_f, g_f) = metrics_by_impl["xla"], metrics_by_impl["flash"]
    np.testing.assert_allclose(l_x, l_f, rtol=1e-5)
    np.testing.assert_allclose(g_x, g_f, rtol=1e-3)


def test_flan_t5_xl_hot_paths_select_flash():
    """BASELINE config 4 (flan-t5-xl: 32 heads, d_kv 64, src 1024/tgt 128)
    must select flash on its training hot paths on a single TPU chip — the
    config VERDICT r2 flagged as 'will train entirely on XLA attention'.
    The learned relative-position bias rides the kernel's differentiable
    learned_bias input there (T5Attention._attend)."""
    single = dict(use_cache=False, mesh=None, backend="tpu", device_count=1)
    # encoder self-attention: 1024×1024 scores, learned bias present
    impl, _ = select_attention_impl(
        "auto", batch=8, heads=32, head_dim=64, q_len=1024, kv_len=1024,
        causal=False, bias_kv_only=False, **single,
    )
    assert impl == "flash"
    # decoder self-attention (teacher-forced): causal 128×128
    impl, _ = select_attention_impl(
        "auto", batch=8, heads=32, head_dim=64, q_len=128, kv_len=128,
        causal=True, bias_kv_only=False, **single,
    )
    assert impl == "flash"
    # cross-attention: mask-only bias, 128×1024
    impl, _ = select_attention_impl(
        "auto", batch=8, heads=32, head_dim=64, q_len=128, kv_len=1024,
        causal=False, bias_kv_only=True, **single,
    )
    assert impl == "flash"
    # decode steps (q_len 1) stay on the XLA cache path
    impl, _ = select_attention_impl(
        "auto", batch=8, heads=32, head_dim=64, q_len=1, kv_len=1024,
        use_cache=True, mesh=None, backend="tpu", device_count=1,
    )
    assert impl == "xla"


def test_lbias_sharded_matches_xla_incl_dbias(mesh8):
    """Multi-device learned-bias flash (hand-written vjp, dbias psummed
    across batch shards) must reproduce XLA attention values AND all
    gradients — including the learned bias's, whose reduction over batch
    shards is the part generic shard_map autodiff can't provide under
    check_vma=False."""
    from distributed_llms_example_tpu.ops.attention import (
        dot_product_attention,
        make_causal_bias,
    )
    from distributed_llms_example_tpu.ops.flash_attention import (
        flash_attention_lbias_sharded,
    )

    rs = np.random.RandomState(3)
    B, H, S, D = 8, 4, 128, 16
    q = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    lb = jnp.asarray(rs.randn(1, H, S, S).astype(np.float32) * 0.5)
    mask = np.zeros((B, 1, 1, S), np.float32)
    mask[:, :, :, -16:] = -1e9
    mask = jnp.asarray(mask)

    for causal in (False, True):
        def f_sharded(q, k, v, lb):
            out = flash_attention_lbias_sharded(
                q, k, v, mask, lb, mesh=mesh8,
                batch_axes=("data", "fsdp"), head_axis="tensor",
                causal=causal, scale=1.0,
            )
            return jnp.sum(out ** 2)

        def f_ref(q, k, v, lb):
            bias = mask + lb + (make_causal_bias(S, S) if causal else 0.0)
            return jnp.sum(dot_product_attention(q, k, v, bias, scale=1.0) ** 2)

        va, ga = jax.value_and_grad(f_sharded, argnums=(0, 1, 2, 3))(q, k, v, lb)
        vb, gb = jax.value_and_grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, lb)
        np.testing.assert_allclose(float(va), float(vb), rtol=1e-4)
        for name, a, b in zip("dq dk dv dlbias".split(), ga, gb):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, rtol=2e-3,
                err_msg=f"causal={causal} {name}",
            )
