"""Fused Pallas clip+AdamW apply (ops/fused_optim.py, --optim-impl).

The contract under test: given identical (params, opt_state, grads), the
fused apply reproduces the optax chain EXACTLY up to XLA's float
contraction — the op sequence is identical, so every element matches
bit-for-bit except where the backend fuses a multiply-add into an FMA in
one compilation and not the other (measured: ≤1 element per few
thousand, ≤1 intermediate ulp, amplified only through cancellation in
``p + u``).  The tests therefore pin floats with
``assert_array_max_ulp`` at single-digit-ulp bounds, and pin EXACTLY:
the opt-state pytree structure (byte-for-byte optax's — checkpoints
roam between impls), integer counts, and every within-one-program
comparison (donation on/off, checkpoint-vs-no-checkpoint), where no
recompilation exists to re-roll the contraction dice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.ops.fused_optim import (
    STAT_NONFINITE,
    STAT_P_SUMSQ,
    STAT_U_SUMSQ,
    adamw_leaf_reference,
    default_impl,
    fused_adamw_leaf,
    fused_adamw_supported,
    resolve_impl,
    set_default_impl,
)
from distributed_llms_example_tpu.parallel.sharding import shard_params
from distributed_llms_example_tpu.train.optim import (
    build_fused_plan,
    fused_optimizer_apply,
    make_optimizer_bundle,
    optimizer_update,
    parse_adamw_state,
    rebuild_adamw_state,
)
from distributed_llms_example_tpu.train.step import (
    create_train_state,
    make_train_step,
    optimizer_apply_block,
    put_batch,
    state_shardings,
)


def _toy_batch(b=8, src=16, tgt=8, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
    attn = np.ones((b, src), np.int32)
    labels = rng.randint(2, vocab, (b, tgt)).astype(np.int32)
    labels[:, -2:] = LABEL_PAD
    return {"input_ids": input_ids, "attention_mask": attn, "labels": labels}


@pytest.fixture(scope="module")
def setup():
    lm = load_model("t5-test")
    params = jax.device_get(lm.init_params(0))
    return lm, params


@pytest.fixture(scope="module")
def mesh1():
    return build_mesh(
        MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1]
    )


def _sharded_state(params, tx, mesh):
    state = create_train_state(shard_params(params, mesh), tx)
    sh = state_shardings(state, mesh)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh), sh


def _synthetic_grads(params, sh=None, scale=0.05):
    rng = np.random.RandomState(7)
    g = jax.tree.map(
        lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32) * scale, params
    )
    if sh is not None:
        g = jax.tree.map(lambda x, s: jax.device_put(x, s), g, sh.params)
    return g


def _plan(spec, tx, sh, mesh, params):
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )
    plan = build_fused_plan(spec, tx, sh, mesh, abstract_params=abstract)
    assert plan is not None
    return plan


def _assert_trees_bit_equal(a, b, what=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=what)


def _assert_trees_equal_mod_fma(a, b, what="", atol=2e-7, rtol=1e-6):
    """Exact for integer leaves; floats within the residue XLA's
    per-compilation FMA contraction can leave between two runs of the
    identical op sequence: a 1-ulp intermediate difference amplified
    through Adam's divide-by-sqrt and the ``p + (-lr·u)`` cancellation
    stays under ~lr·1e-4 absolute (measured 6e-8 at lr=1e-3)."""
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        la, lb = np.asarray(la), np.asarray(lb)
        if np.issubdtype(la.dtype, np.integer):
            np.testing.assert_array_equal(la, lb, err_msg=what)
        else:
            np.testing.assert_allclose(la, lb, atol=atol, rtol=rtol, err_msg=what)


# ---------------------------------------------------------------- impl knob


def test_resolve_impl_and_default_knob():
    assert resolve_impl("xla") == "xla"
    assert resolve_impl("fused") == "fused"
    # auto on this (CPU) suite resolves to the optax chain
    assert resolve_impl("auto") == "xla"
    assert resolve_impl("auto", backend="tpu") == "fused"
    prev = default_impl()
    try:
        set_default_impl("fused")
        assert resolve_impl(None) == "fused"
    finally:
        set_default_impl(prev)
    with pytest.raises(ValueError, match="optim impl"):
        set_default_impl("nope")
    with pytest.raises(ValueError, match="optim impl"):
        resolve_impl("nope")


def test_fused_supported_gate():
    assert fused_adamw_supported(16 * 256)  # flattens to 8-aligned x 128k
    assert fused_adamw_supported(1024)
    assert not fused_adamw_supported(64)  # sub-tile leaf (norm scale)
    assert not fused_adamw_supported(1000)  # not a multiple of 8*128
    assert not fused_adamw_supported(1024, dtype=jnp.bfloat16)  # f32 only


# ------------------------------------------------- kernel vs reference leaf


@pytest.mark.parametrize("trigger", [0.0, 1.0])
@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_kernel_leaf_bit_equal_vs_reference(trigger, wd):
    """The Pallas kernel (interpret mode) reproduces the jnp reference
    leaf bit-for-bit for both clip branches and both decay settings, and
    its health partial sums match the reference's reductions."""
    rng = np.random.RandomState(0)
    shape = (16, 256)
    p = jnp.asarray(rng.randn(*shape), jnp.float32)
    mu = jnp.asarray(rng.randn(*shape) * 0.01, jnp.float32)
    nu = jnp.asarray(np.abs(rng.randn(*shape)) * 1e-3, jnp.float32)
    g = jnp.asarray(rng.randn(*shape), jnp.float32)
    # scalars as the tree apply computes them (gnorm/bias corrections/lr)
    scal = jnp.asarray(
        [3.7, trigger, 0.1, 0.001, -1e-3, 0.0, 0.0, 0.0], jnp.float32
    )
    hyper = dict(b1=0.9, b2=0.999, eps=1e-8, max_norm=1.0, wd=wd)
    k = jax.jit(
        lambda *a: fused_adamw_leaf(*a, interpret=True, **hyper)
    )(p, mu, nu, g, scal)
    r = jax.jit(lambda *a: adamw_leaf_reference(*a, **hyper))(p, mu, nu, g, scal)
    for i, name in enumerate(("params", "mu", "nu")):
        _assert_trees_equal_mod_fma(k[i], r[i], what=name)
    # stats: sums over different tile orders — equal to float tolerance
    np.testing.assert_allclose(
        np.asarray(k[3][:3]), np.asarray(r[3][:3]), rtol=1e-6
    )


def test_kernel_counts_nonfinite():
    shape = (8, 128)
    p = jnp.ones(shape, jnp.float32)
    mu = jnp.zeros(shape, jnp.float32)
    nu = jnp.zeros(shape, jnp.float32)
    g = jnp.ones(shape, jnp.float32).at[0, 0].set(jnp.nan).at[1, 1].set(jnp.inf)
    scal = jnp.asarray([1.0, 1.0, 0.1, 0.001, -1e-3, 0, 0, 0], jnp.float32)
    out = fused_adamw_leaf(
        p, mu, nu, g, scal, b1=0.9, b2=0.999, eps=1e-8, max_norm=0.0, wd=0.0,
        interpret=True,
    )
    assert float(out[3][STAT_NONFINITE]) == 2.0
    assert float(out[3][STAT_P_SUMSQ]) == float(np.prod(shape))
    # non-finite grads poison the update itself — its sumsq goes NaN, and
    # the watchdog's tripwire reads the COUNT, which stays exact
    assert not np.isfinite(float(out[3][STAT_U_SUMSQ]))
    # the reference path must count the PRE-clip stream too: with clip ON
    # a NaN gradient makes the global norm NaN and the clip branch
    # NaN-floods the whole leaf — counting post-clip would report
    # leaf-size instead of the true 2 (the tripwire's only signal)
    nan_scal = jnp.asarray(
        [jnp.nan, 0.0, 0.1, 0.001, -1e-3, 0, 0, 0], jnp.float32
    )
    for fn in (fused_adamw_leaf, adamw_leaf_reference):
        kw = {"interpret": True} if fn is fused_adamw_leaf else {}
        r = fn(p, mu, nu, g, nan_scal, b1=0.9, b2=0.999, eps=1e-8,
               max_norm=1.0, wd=0.0, **kw)
        assert float(r[3][STAT_NONFINITE]) == 2.0, fn.__name__


# ----------------------------------------- tree apply vs the optax chain


def test_apply_bit_equal_vs_optax_single_device(setup, mesh1):
    """Identical (params, opt_state, grads) → the fused tree apply and
    the optax chain produce bit-equal params and opt_state (and the same
    grad-norm scalar) — kernel leaves and jnp-fallback leaves alike."""
    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    state, sh = _sharded_state(params, tx, mesh1)
    grads = _synthetic_grads(state.params, sh)
    plan = _plan(spec, tx, sh, mesh1, state.params)

    def apply_xla(state, grads):
        new_p, new_opt, _u = optimizer_update(tx, grads, state.opt_state, state.params)
        return new_p, new_opt, optax.global_norm(grads)

    def apply_fused(state, grads):
        new_p, new_opt, gnorm, _stats = fused_optimizer_apply(
            plan, schedule, state.params, state.opt_state, grads
        )
        return new_p, new_opt, gnorm

    ax = jax.jit(apply_xla)(state, grads)
    af = jax.jit(apply_fused)(state, grads)
    _assert_trees_equal_mod_fma(ax[0], af[0], "params")
    _assert_trees_equal_mod_fma(ax[1], af[1], "opt_state")
    assert float(ax[2]) == float(af[2])
    # the rebuilt opt_state is the SAME optax pytree, not a private format
    assert jax.tree_util.tree_structure(ax[1]) == jax.tree_util.tree_structure(af[1])


def test_apply_bit_equal_on_mesh8(setup, mesh8):
    """The per-shard shard_map kernel path (8-device mesh, fsdp+tensor
    sharded leaves) stays bit-equal to the optax chain — the elementwise
    update is shard-local and the two-stage grad-norm psum matches
    GSPMD's reduction for the chain."""
    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    state, sh = _sharded_state(params, tx, mesh8)
    grads = _synthetic_grads(state.params, sh)
    plan = _plan(spec, tx, sh, mesh8, state.params)
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    ax = jax.jit(
        lambda s, g: optimizer_update(tx, g, s.opt_state, s.params)[:2]
    )(state, grads)
    with activation_mesh(mesh8):
        af = jax.jit(
            lambda s, g: fused_optimizer_apply(
                plan, schedule, s.params, s.opt_state, g
            )[:2]
        )(state, grads)
    _assert_trees_equal_mod_fma(ax[0], af[0], "params")
    _assert_trees_equal_mod_fma(ax[1], af[1], "opt_state")


def test_one_program_step_bit_equal_with_accum(setup, mesh1):
    """The strongest cross-impl pin: ONE compiled program computes the
    grad-accumulation scan once (accum=2, uneven token counts) and feeds
    the identical sums to BOTH optimizer_apply_block impls — outputs are
    bit-equal, so the fused apply transitively satisfies every oracle
    the xla path is pinned against (optax.MultiSteps, PR 5)."""
    from distributed_llms_example_tpu.train.step import make_loss_fn

    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    state, sh = _sharded_state(params, tx, mesh1)
    plan = _plan(spec, tx, sh, mesh1, state.params)
    loss_sums = make_loss_fn(lm.module, lm.config, 0.0, is_seq2seq=True)
    batch = _toy_batch(b=8)
    batch["labels"][0:2, 3:] = LABEL_PAD  # uneven tokens across microbatches
    N = 2

    def both(state, batch):
        micro = jax.tree.map(
            lambda x: jnp.swapaxes(
                x.reshape(x.shape[0] // N, N, *x.shape[1:]), 0, 1
            ),
            batch,
        )

        def body(carry, mb):
            lsum_a, tok_a, g_a = carry
            (lsum, tokens), g = jax.value_and_grad(
                lambda p: loss_sums(p, mb, None), has_aux=True
            )(state.params)
            g_a = jax.tree.map(lambda a, gg: a + gg.astype(jnp.float32), g_a, g)
            return (lsum_a + lsum, tok_a + tokens, g_a), None

        zero = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )
        (lsum, tokens, grads), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), zero), micro
        )
        s_x, m_x = optimizer_apply_block(
            state, tx, schedule, lsum, tokens, grads, health=False
        )
        s_f, m_f = optimizer_apply_block(
            state, tx, schedule, lsum, tokens, grads, health=False, fused=plan
        )
        return s_x, s_f, m_x, m_f

    s_x, s_f, m_x, m_f = jax.jit(both)(state, put_batch(batch, mesh1))
    _assert_trees_equal_mod_fma(s_x.params, s_f.params, "params")
    _assert_trees_equal_mod_fma(s_x.opt_state, s_f.opt_state, "opt_state")
    assert float(m_x["loss"]) == float(m_f["loss"])
    assert float(m_x["grad_norm"]) == float(m_f["grad_norm"])
    assert int(jax.device_get(s_f.step)) == 1


def test_state_parse_and_rebuild_roundtrip(setup):
    """parse/rebuild preserve the optax chain's pytree structure exactly
    and advance every count by one — the layout contract checkpoints
    depend on."""
    lm, params = setup
    tx, _, _ = make_optimizer_bundle()
    st = tx.init(params)
    adam, scheds = parse_adamw_state(st)
    assert int(adam.count) == 0 and len(scheds) == 1
    new_adam = optax.ScaleByAdamState(
        count=adam.count + 1, mu=adam.mu, nu=adam.nu
    )
    rebuilt = rebuild_adamw_state(st, new_adam)
    assert jax.tree_util.tree_structure(rebuilt) == jax.tree_util.tree_structure(st)
    adam2, scheds2 = parse_adamw_state(rebuilt)
    assert int(adam2.count) == 1 and int(scheds2[0].count) == 1
    # a non-adamw chain is refused (callers fall back to xla)
    with pytest.raises(ValueError, match="ScaleByAdamState"):
        parse_adamw_state(optax.sgd(1e-2).init(params))


def test_build_fused_plan_falls_back_on_foreign_chain(setup, mesh1, capsys):
    """An opt chain the fused path cannot parse (plain SGD) yields None
    (with a logged reason) instead of a trace-time crash — the step then
    runs the xla impl."""
    lm, params = setup
    _, _, spec = make_optimizer_bundle()
    tx = optax.sgd(1e-2)
    state = create_train_state(shard_params(params, mesh1), tx)
    sh = state_shardings(state, mesh1)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state.params
    )
    plan = build_fused_plan(spec, tx, sh, mesh1, abstract_params=abstract)
    assert plan is None
    assert "fused_optim_fallback" in capsys.readouterr().out


# ------------------------------------------------- full train-step coverage


def test_full_step_fused_runs_and_matches_loss(setup, mesh8):
    """--optim-impl fused through the real make_train_step on the 8-device
    mesh: the forward is untouched (loss bit-equal to the xla step), the
    trajectory stays within ulp-accumulation distance (separately
    compiled programs may fuse the backward differently — the one-program
    test above pins the apply math bitwise), and the state's step counter
    advances once per step."""
    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    batch = _toy_batch()
    outs = {}
    for impl in ("xla", "fused"):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, donate=False,
            optim_spec=spec, optim_impl=impl,
        )
        state, sh = _sharded_state(params, tx, mesh8)
        step, _ = build(state)
        gb = put_batch(batch, mesh8)
        losses = []
        for _ in range(3):
            state, metrics = step(state, gb)
            losses.append(float(metrics["loss"]))
        outs[impl] = (losses, jax.device_get(state.params))
    # first-step loss depends only on the (identical) forward
    assert outs["xla"][0][0] == outs["fused"][0][0]
    assert outs["fused"][0][-1] < outs["fused"][0][0]
    for a, b in zip(jax.tree.leaves(outs["xla"][1]), jax.tree.leaves(outs["fused"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6, rtol=2e-6)


@pytest.mark.slow  # two extra full-step compiles (donate on/off): slow tier
def test_fused_step_donation_safe(setup, mesh8):
    """donate=True with the fused in-place apply must not corrupt the
    trajectory: a 3-step donated run equals the non-donated one exactly
    (buffer aliasing is a memory optimization, never a value change)."""
    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    batch = _toy_batch()
    trajectories = {}
    for donate in (False, True):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, donate=donate,
            grad_accum_steps=2, optim_spec=spec, optim_impl="fused",
        )
        state, _ = _sharded_state(params, tx, mesh8)
        step, _ = build(state)
        gb = put_batch(batch, mesh8)
        losses = []
        for _ in range(3):
            state, metrics = step(state, gb)
            losses.append(float(metrics["loss"]))
        trajectories[donate] = (losses, jax.device_get(state.params))
    l_no, p_no = trajectories[False]
    l_yes, p_yes = trajectories[True]
    assert l_yes == l_no
    _assert_trees_bit_equal(p_no, p_yes, "donated params")


@pytest.mark.slow  # a health-enabled fused compile: slow tier
def test_fused_health_from_kernel_stats(setup, mesh8):
    """health=True under the fused impl sources the numerics from the
    kernel's partial sums: same keys, values matching the xla health
    bundle to reduction-order tolerance, nonfinite exact."""
    from distributed_llms_example_tpu.train.step import HEALTH_METRIC_KEYS

    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    batch = _toy_batch()
    metrics_by_impl = {}
    for impl in ("xla", "fused"):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, donate=False,
            health=True, optim_spec=spec, optim_impl=impl,
        )
        state, _ = _sharded_state(params, tx, mesh8)
        step, _ = build(state)
        _, metrics = step(state, put_batch(batch, mesh8))
        metrics_by_impl[impl] = {k: float(metrics[k]) for k in HEALTH_METRIC_KEYS}
    mx, mf = metrics_by_impl["xla"], metrics_by_impl["fused"]
    assert mf["nonfinite_count"] == 0.0 == mx["nonfinite_count"]
    for k in HEALTH_METRIC_KEYS:
        np.testing.assert_allclose(mf[k], mx[k], rtol=1e-4, atol=1e-9, err_msg=k)


@pytest.mark.slow  # two step compiles + orbax round-trips: slow tier
def test_checkpoint_roundtrip_across_impls(setup, mesh8, tmp_path):
    """The satellite pin: a checkpoint SAVED under --optim-impl fused
    restores and continues under xla (and vice versa) with a trajectory
    BIT-EQUAL to the same impl switch without any checkpoint — the fused
    kernel's mu/nu ride the standard optax pytree, so the save/restore
    is a pure pass-through, not a format translation."""
    from distributed_llms_example_tpu.io.checkpoint import Checkpointer, abstract_like

    lm, params = setup
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    batch = _toy_batch()
    steps = {}
    for impl in ("fused", "xla"):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, donate=False,
            optim_spec=spec, optim_impl=impl,
        )
        state, sh = _sharded_state(params, tx, mesh8)
        steps[impl] = (build(state)[0], sh)
    gb = put_batch(batch, mesh8)

    for first, then in (("fused", "xla"), ("xla", "fused")):
        # reference: impl switch mid-run, no checkpoint
        state, sh = _sharded_state(params, tx, mesh8)
        for _ in range(2):
            state, _m = steps[first][0](state, gb)
        mid_ref = state
        for _ in range(2):
            state, _m = steps[then][0](state, gb)
        ref = jax.device_get(state)

        # the same switch THROUGH a checkpoint
        state, sh = _sharded_state(params, tx, mesh8)
        for _ in range(2):
            state, _m = steps[first][0](state, gb)
        ckpt = Checkpointer(
            str(tmp_path / f"ckpt-{first}"), save_every_steps=1, async_save=False
        )
        assert ckpt.save(2, state, force=True)
        ckpt.wait()
        restored = ckpt.restore_latest(abstract_like(state, sh))
        assert restored is not None
        state, step_no = restored
        assert step_no == 2
        _assert_trees_bit_equal(state, mid_ref, "restored state")
        for _ in range(2):
            state, _m = steps[then][0](state, gb)
        got = jax.device_get(state)
        _assert_trees_bit_equal(ref.params, got.params, f"{first}->{then} params")
        _assert_trees_bit_equal(
            ref.opt_state, got.opt_state, f"{first}->{then} opt_state"
        )


# ----------------------------------------------------- composition / spans


def test_composition_row_fused_optim_pipelined():
    from distributed_llms_example_tpu.analysis.composition import (
        config_flags,
        failing_combos,
        validate_composition,
    )

    # auto NEVER sets the flag (it resolves to xla under a pipeline)
    assert "fused_optim" not in config_flags(pipelined=True, optim_impl="auto")
    flags = config_flags(pipelined=True, optim_impl="fused")
    assert "fused_optim" in flags
    bad = failing_combos(
        family="llama", schedule="gpipe",
        mesh_axes={"stage": 2, "data": 4}, flags=flags,
    )
    assert any(row.id == "fused-optim-pipelined" for row in bad)
    with pytest.raises(ValueError, match="optim-impl fused"):
        validate_composition(
            family="llama", schedule="gpipe",
            mesh_axes={"stage": 2, "data": 4}, flags=flags,
        )
    # without a pipeline the combo is clean
    assert not failing_combos(
        family="llama", mesh_axes={"data": 8},
        flags=config_flags(pipelined=False, optim_impl="fused"),
    )


def test_once_per_step_spans_cover_fused_layer():
    """The IR census's source spans include the fused-apply layer, so the
    once-per-step placement proof keeps working when --optim-impl fused
    moves the apply's instructions into ops/fused_optim.py frames."""
    from distributed_llms_example_tpu.train.step import once_per_step_source_spans

    spans = once_per_step_source_spans()
    files = {f for f, _a, _b in spans}
    assert any(f.endswith("ops/fused_optim.py") for f in files)
    assert any(f.endswith("train/optim.py") for f in files)
    assert any(f.endswith("train/step.py") for f in files)


@pytest.mark.slow  # an AOT fsdp=8 fused-step compile + HLO text scan: slow tier
def test_fused_step_once_per_step_and_in_place_on_compiled_hlo(setup):
    """The two compiled-program contracts for --optim-impl fused, pinned
    on a pure-FSDP accum=2 step's real HLO: (1) the once-per-step census
    still attributes the apply (now in ops/fused_optim.py frames) and
    finds NONE of it inside the grad-accumulation scan body; (2) the
    in-place contract — zero span-attributed f32 param-sized copy
    instructions survive (input_output_aliases did its job)."""
    import math

    from distributed_llms_example_tpu.analysis.ir_lint import (
        in_place_apply_finding,
        once_per_step_finding,
        once_per_step_placement,
    )
    from distributed_llms_example_tpu.train.step import once_per_step_source_spans

    lm, params = setup
    mesh = build_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh, grad_accum_steps=2,
        donate=False, optim_spec=spec, optim_impl="fused",
    )
    state, _sh = _sharded_state(params, tx, mesh)
    step, _ = build(state)
    batch = _toy_batch(b=16)
    text = step.jitted.lower(state, put_batch(batch, mesh)).compile().as_text()
    spans = once_per_step_source_spans()
    # the compiled text is the PER-DEVICE program: match shard counts too
    # (the same candidate expansion lint_train_step applies)
    from distributed_llms_example_tpu.analysis.ir_lint import (
        model_tree_element_candidates,
    )

    elems = model_tree_element_candidates(
        [int(math.prod(x.shape)) for x in jax.tree.leaves(state.params)], 8
    )
    # floor just above the known tiny layout-relayout noise (512-elem
    # transpose copies on sub-tile fallback leaves) so embedding-scale
    # copies of this toy model would still be caught; production uses
    # MIN_COPY_CENSUS_ELEMS, far under any 7B leaf shard
    census = once_per_step_placement(
        text, spans, param_elems=elems, min_copy_elems=1024
    )
    assert census["total"] > 0, "fused-apply source spans missing from HLO"
    assert census["in_loop"] == 0, census
    assert census["fp32_param_copies"] == 0, census["fp32_copy_examples"]
    assert once_per_step_finding(text, spans) is None
    assert in_place_apply_finding(text, spans, elems, min_copy_elems=1024) is None


def test_ragged_sharded_leaf_falls_back_to_reference(setup, mesh8):
    """A leaf whose spec'd dim does NOT divide its mesh axes must take
    the (GSPMD-padded) reference path — the total element count can be
    kernel-tileable while shard_map would reject the ragged split."""
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.ops.fused_optim import _spec_divides

    # mesh8: data=2, fsdp=2, tensor=2 — dim 0 of 6 over 4 shards is ragged
    assert not _spec_divides((6, 4096), P(("data", "fsdp")), mesh8)
    assert _spec_divides((8, 4096), P(("data", "fsdp")), mesh8)
    assert _spec_divides((6, 4096), P(None, "tensor"), mesh8)

    # end to end: a hand-built tree with one ragged-but-tileable leaf
    # (6*4096 elems pass fused_adamw_supported) runs through the fused
    # apply on the mesh without tripping shard_map, matching the chain
    from distributed_llms_example_tpu.train.optim import FusedOptimPlan

    tx, schedule, spec = make_optimizer_bundle(
        learning_rate=1e-3, warmup_steps=0, total_steps=100
    )
    params = {"w": jnp.asarray(np.random.RandomState(0).randn(6, 4096), jnp.float32)}
    state = create_train_state(params, tx)
    grads = {"w": jnp.full((6, 4096), 0.01, jnp.float32)}
    plan = FusedOptimPlan(
        spec=spec, mesh=mesh8, param_specs={"w": P(("data", "fsdp"))}
    )
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    with activation_mesh(mesh8):
        new_p, new_opt, gnorm, _stats = jax.jit(
            lambda s, g: fused_optimizer_apply(
                plan, schedule, s.params, s.opt_state, g
            )
        )(state, grads)
    ax = jax.jit(
        lambda s, g: optimizer_update(tx, g, s.opt_state, s.params)[:2]
    )(state, grads)
    _assert_trees_equal_mod_fma(ax[0], new_p, "ragged params")
