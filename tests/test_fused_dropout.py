"""Fused Pallas dropout (ops/fused_dropout.py) + flash-attention probs
dropout — interpret-mode kernel tests on CPU.

Acceptance pins (ISSUE 4):
- CPU interpret-mode parity: fused forward+backward match reference
  dropout EXACTLY when fed the identical mask (reconstructed from the
  same counter-hash stream via ``hash_keep_mask``), and keep-rate
  statistics hold for the in-kernel RNG.
- determinism for equal seeds, independence for different seeds;
- forward/backward mask agreement via custom_vjp grad check;
- composition with remat;
- the flash-attention causal/cross/learned-bias variants with in-kernel
  probs dropout against an explicit-mask reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.ops.fused_dropout import (
    Dropout,
    default_impl,
    dropout,
    fused_dropout,
    fused_dropout_supported,
    hash_keep_mask,
    keep_threshold,
    resolve_impl,
    seed_from_key,
    set_default_impl,
)
from distributed_llms_example_tpu.ops.flash_attention import flash_attention

SEED = jnp.int32(1234)


def _x(shape, key=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


def _ref(x, mask, rate):
    """The kernel's exact arithmetic: fp32 multiply by 1/(1-rate), cast."""
    inv = np.float32(1.0 / (1.0 - rate))
    return jnp.where(mask, x.astype(jnp.float32) * inv, 0.0).astype(x.dtype)


# ------------------------------------------------------------ the raw op


def test_keep_rate_statistics():
    """In-kernel RNG keep rate lands within tolerance of 1-rate, and the
    inverted scaling keeps the mean (the statistical contract)."""
    x = jnp.ones((512, 512))
    for rate in (0.1, 0.5):
        y = fused_dropout(x, SEED, rate)
        dropped = float((y == 0).mean())
        assert abs(dropped - rate) < 0.01, (rate, dropped)
        assert abs(float(y.mean()) - 1.0) < 0.02


def test_equal_seeds_equal_masks_different_seeds_differ():
    x = _x((64, 256))
    a = fused_dropout(x, SEED, 0.2)
    b = fused_dropout(x, SEED, 0.2)
    assert (a == b).all()
    c = fused_dropout(x, jnp.int32(4321), 0.2)
    assert (a != c).any()


def test_forward_matches_reference_given_identical_mask():
    """The pure hash_keep_mask IS the kernel's mask: forward output equals
    the reference dropout fed that mask, bit for bit."""
    x = _x((64, 256))
    mask = hash_keep_mask(SEED, (64, 256), 0.1)
    assert (fused_dropout(x, SEED, 0.1) == _ref(x, mask, 0.1)).all()


def test_forward_mask_is_blocking_independent():
    """The hash stream depends only on absolute element position, so a
    3-D activation reshaped by the kernel sees the same mask as its 2-D
    flattening."""
    x3 = _x((4, 16, 256))
    y3 = fused_dropout(x3, SEED, 0.25)
    y2 = fused_dropout(x3.reshape(64, 256), SEED, 0.25)
    assert (y3.reshape(64, 256) == y2).all()


def test_backward_recomputes_identical_mask():
    """custom_vjp grad check: the backward redraws the mask from the seed
    (zero residual bytes) and must agree exactly with the reference-mask
    gradient."""
    x = _x((64, 256))
    w = _x((64, 256), key=1)
    mask = hash_keep_mask(SEED, (64, 256), 0.1)
    g = jax.grad(lambda x: (fused_dropout(x, SEED, 0.1) * w).sum())(x)
    g_ref = jax.grad(lambda x: (_ref(x, mask, 0.1) * w).sum())(x)
    assert (g == g_ref).all()


def test_residual_fusion_forward_and_grads():
    """dropout(h, residual=r) == r + dropout(h) in one pass; d/dresidual
    is the identity."""
    x, r, w = _x((64, 256)), _x((64, 256), 1), _x((64, 256), 2)
    mask = hash_keep_mask(SEED, (64, 256), 0.3)
    y = fused_dropout(x, SEED, 0.3, residual=r)
    assert (y == r + _ref(x, mask, 0.3)).all()
    gx, gr = jax.grad(
        lambda x, r: (fused_dropout(x, SEED, 0.3, residual=r) * w).sum(),
        argnums=(0, 1),
    )(x, r)
    g_ref = jax.grad(lambda x: (_ref(x, mask, 0.3) * w).sum())(x)
    assert (gx == g_ref).all()
    assert (gr == w).all()


def test_composes_with_remat():
    """jax.checkpoint replays the forward: the seed-recompute stream must
    hand the replay the identical mask (this is what makes the op carry
    ZERO residual bytes under remat)."""
    x = _x((64, 256))
    w = _x((64, 256), 1)

    def f(x):
        return (fused_dropout(x, SEED, 0.2) * w).sum()

    g_plain = jax.grad(f)(x)
    g_remat = jax.grad(jax.checkpoint(f))(x)
    assert (g_plain == g_remat).all()


def test_bf16_and_jit():
    x = _x((8, 32, 128), dtype=jnp.bfloat16)
    y = jax.jit(lambda x: fused_dropout(x, SEED, 0.5))(x)
    assert y.dtype == jnp.bfloat16 and y.shape == x.shape
    assert 0.3 < float((y == 0).mean()) < 0.7


def test_supported_gate():
    assert fused_dropout_supported((64, 256))
    assert not fused_dropout_supported((64, 100))   # sub-lane feature dim
    assert not fused_dropout_supported((3, 128))    # rows not 8-tileable
    assert not fused_dropout_supported((256,))      # 1-D
    assert not fused_dropout_supported((64, 256), rate=0.0)
    with pytest.raises(ValueError):
        fused_dropout(_x((64, 100)), SEED, 0.1)


def test_keep_threshold_is_24bit_exact():
    assert keep_threshold(0.0) == 1 << 24
    assert keep_threshold(1.0) == 0
    assert keep_threshold(0.5) == 1 << 23


# ------------------------------------------------- helper / module layer


def test_seed_from_key_deterministic_and_impl_agnostic():
    k = jax.random.PRNGKey(7)
    assert int(seed_from_key(k)) == int(seed_from_key(jax.random.PRNGKey(7)))
    assert int(seed_from_key(k)) != int(seed_from_key(jax.random.fold_in(k, 1)))
    # typed keys (threefry and the rbg hardware stream) fold too
    assert seed_from_key(jax.random.key(7)).dtype == jnp.int32
    assert seed_from_key(jax.random.key(7, impl="rbg")).dtype == jnp.int32


def test_resolve_impl_auto_follows_backend():
    assert resolve_impl("auto", backend="tpu") == "fused"
    assert resolve_impl("auto", backend="cpu") == "xla"
    assert resolve_impl("fused", backend="cpu") == "fused"
    with pytest.raises(ValueError):
        resolve_impl("bogus")
    prev = default_impl()
    try:
        set_default_impl("fused")
        assert resolve_impl(None, backend="cpu") == "fused"
    finally:
        set_default_impl(prev)
    with pytest.raises(ValueError):
        set_default_impl("bogus")


def test_module_xla_path_is_bit_identical_to_nn_dropout():
    """Existing training behavior must not move: the helper's xla path
    reproduces flax.linen.Dropout exactly (same rng collection, same
    bernoulli call, same select)."""
    import flax.linen as nn

    x = _x((4, 32, 128))
    rngs = {"dropout": jax.random.PRNGKey(5)}
    ours = Dropout(0.2, impl="xla").apply({}, x, False, rngs=rngs)
    flax_ = nn.Dropout(0.2, deterministic=False).apply({}, x, rngs=rngs)
    assert (ours == flax_).all()


def test_module_fused_path_and_residual():
    x, r = _x((4, 32, 128)), _x((4, 32, 128), 1)
    rngs = {"dropout": jax.random.PRNGKey(5)}
    y = Dropout(0.2, impl="fused").apply({}, x, False, residual=r, rngs=rngs)
    # identical call → identical output (determinism through make_rng)
    y2 = Dropout(0.2, impl="fused").apply({}, x, False, residual=r, rngs=rngs)
    assert (y == y2).all()
    dropped = float((y - r == 0).mean())
    assert abs(dropped - 0.2) < 0.02


def test_module_deterministic_and_zero_rate_are_identity():
    x, r = _x((4, 32, 128)), _x((4, 32, 128), 1)
    assert (Dropout(0.2).apply({}, x, True) == x).all()
    assert (Dropout(0.0).apply({}, x, False) == x).all()
    assert (Dropout(0.2).apply({}, x, True, residual=r) == x + r).all()


def test_functional_unsupported_shape_falls_back_to_xla():
    """A feature dim the kernel cannot tile silently takes the reference
    path — correctness never depends on tileability."""
    x = _x((16, 100))
    key = jax.random.PRNGKey(3)
    fused = dropout(x, key, 0.2, impl="fused")
    xla = dropout(x, key, 0.2, impl="xla")
    assert (fused == xla).all()


def test_functional_no_mesh_multidevice_falls_back_to_xla():
    """On a multi-device backend with NO mesh context (e.g. inside the
    pipeline's partial-manual regions) an opaque pallas call would force
    GSPMD gathers — the helper must take the XLA path, same rule as
    flash attention.  The test env has 8 virtual CPU devices."""
    x = _x((64, 256))
    key = jax.random.PRNGKey(11)
    assert jax.device_count() > 1
    y_fn = dropout(x, key, 0.4, impl="fused")
    assert (y_fn == dropout(x, key, 0.4, impl="xla")).all()


def test_functional_fused_under_mesh_shard_map(dp_mesh):
    """Under an ambient mesh the helper runs the kernel per-shard with
    axis-folded seeds: deterministic, statistically correct, different
    masks per shard, grads flow."""
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    x = _x((8, 64, 256))
    key = jax.random.PRNGKey(11)
    with activation_mesh(dp_mesh):
        y = dropout(x, key, 0.25, impl="fused")
        y2 = dropout(x, key, 0.25, impl="fused")
        assert (y == y2).all()
        dropped = float((np.asarray(y) == 0).mean())
        assert abs(dropped - 0.25) < 0.02
        # per-shard seed folding: shard 0 and shard 1 draw different masks
        m0 = np.asarray(y[0]) == 0
        m1 = np.asarray(y[1]) == 0
        assert (m0 != m1).any()
        g = jax.grad(
            lambda x: dropout(x, key, 0.25, impl="fused").sum()
        )(x)
        assert bool(jnp.isfinite(g).all())


def test_pipeline_dropout_shim_routes_through_helper():
    """parallel/pipeline.dropout (the adapters' out-of-loop dropout) must
    equal the shared helper bit for bit (xla resolution on CPU)."""
    from distributed_llms_example_tpu.parallel.pipeline import (
        dropout as pipe_dropout,
    )

    x = _x((8, 64, 128))
    key = jax.random.PRNGKey(21)
    assert (pipe_dropout(x, key, 0.1) == dropout(x, key, 0.1, impl="xla")).all()


# ------------------------------------- flash-attention probs dropout


def _qkv(B=2, H=2, S=256, D=64, kv_len=None):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, H, kv_len or S, D), jnp.float32)
    v = jax.random.normal(kv, (B, H, kv_len or S, D), jnp.float32)
    return q, k, v


def _probs_keep(B, H, Sq, Sk, rate, seed=SEED):
    return jnp.stack([
        jnp.stack([
            hash_keep_mask(seed, (Sq, Sk), rate, tag_a=b, tag_b=h)
            for h in range(H)
        ]) for b in range(B)
    ])


def _ref_attn(q, k, v, rate, *, causal=False, scale=None, lbias=None,
              seed=SEED):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (scale if scale is not None else D ** -0.5)
    if lbias is not None:
        s = s + lbias
    if causal:
        m = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(m[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    pd = jnp.where(_probs_keep(B, H, Sq, Sk, rate, seed), p / (1 - rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", pd, v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_probs_dropout_forward(causal):
    q, k, v = _qkv()
    out = flash_attention(
        q, k, v, causal=causal, dropout_rate=0.15, dropout_seed=SEED,
        interpret=True,
    )
    ref = _ref_attn(q, k, v, 0.15, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # deterministic for equal seeds
    out2 = flash_attention(
        q, k, v, causal=causal, dropout_rate=0.15, dropout_seed=SEED,
        interpret=True,
    )
    assert (out == out2).all()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_probs_dropout_grads(causal):
    """Backward kernels redraw the identical in-kernel mask: dq/dk/dv
    match the explicit-mask reference."""
    q, k, v = _qkv()
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def f(q, k, v):
        return (flash_attention(
            q, k, v, causal=causal, dropout_rate=0.15, dropout_seed=SEED,
            interpret=True) * w).sum()

    def f_ref(q, k, v):
        return (_ref_attn(q, k, v, 0.15, causal=causal) * w).sum()

    for g, g_ref in zip(jax.grad(f, (0, 1, 2))(q, k, v),
                        jax.grad(f_ref, (0, 1, 2))(q, k, v)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=2e-5)


def test_flash_probs_dropout_cross_attention():
    """q_len != kv_len (the seq2seq cross-attention shape)."""
    q, k, v = _qkv(S=256, kv_len=128)
    out = flash_attention(
        q, k, v, dropout_rate=0.2, dropout_seed=SEED, interpret=True
    )
    ref = _ref_attn(q, k, v, 0.2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_probs_dropout_learned_bias_grad():
    """T5's differentiable relative-position bias: the dlbias kernel also
    recomputes the mask (batch-innermost grid)."""
    q, k, v = _qkv()
    B, H, S, _ = q.shape
    lb = jax.random.normal(jax.random.PRNGKey(4), (1, H, S, S)) * 0.1
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def f(lb):
        return (flash_attention(
            q, k, v, learned_bias=lb, scale=1.0, dropout_rate=0.15,
            dropout_seed=SEED, interpret=True) * w).sum()

    def f_ref(lb):
        return (_ref_attn(q, k, v, 0.15, scale=1.0, lbias=lb) * w).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(f)(lb)), np.asarray(jax.grad(f_ref)(lb)), atol=2e-4
    )


def test_flash_rate_zero_is_exact_baseline():
    q, k, v = _qkv()
    assert (
        flash_attention(q, k, v, interpret=True)
        == flash_attention(q, k, v, dropout_rate=0.0, interpret=True)
    ).all()


def test_flash_dropout_requires_seed():
    q, k, v = _qkv(S=128)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, dropout_rate=0.1, interpret=True)


def test_flash_probs_keep_rate():
    """Statistical check straight on the kernel output: the zero pattern
    of dropout(softmax)@v is hard to read, so compare against v-ones —
    out row ≈ rowsum(pd) which averages to 1."""
    q, k, v = _qkv()
    v1 = jnp.ones_like(v)
    out = flash_attention(
        q, k, v1, dropout_rate=0.25, dropout_seed=SEED, interpret=True
    )
    assert abs(float(out.mean()) - 1.0) < 0.05


# ------------------------------------------- model-level integration


@pytest.mark.slow  # ~80s: grads through the sharded lbias kernel's
#                  hand-written vjp (8 interpret shards × 4 kernels); the
#                  dlbias+dropout math itself is covered fast by
#                  test_flash_probs_dropout_learned_bias_grad
def test_t5_attn_dropout_routes_through_kernel(dp_mesh):
    """A T5 config with attn_dropout_rate > 0 under a mesh (forced flash →
    the sharded learned-bias kernel path with in-kernel probs dropout):
    deterministic per key, distinct across keys, grads finite."""
    import dataclasses

    from distributed_llms_example_tpu.models.registry import T5_CONFIGS
    from distributed_llms_example_tpu.models.t5 import T5ForConditionalGeneration
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    cfg = dataclasses.replace(
        T5_CONFIGS["t5-test"], attn_dropout_rate=0.2, attention_impl="flash"
    )
    model = T5ForConditionalGeneration(cfg)
    enc = jnp.ones((8, 128), jnp.int32)
    dec = jnp.ones((8, 128), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), enc, None, dec)["params"]

    def run(key, p=params):
        with activation_mesh(dp_mesh):
            return model.apply(
                {"params": p}, enc, None, dec,
                deterministic=False, rngs={"dropout": key},
            )

    a, b = run(jax.random.PRNGKey(1)), run(jax.random.PRNGKey(1))
    assert (a == b).all()
    c = run(jax.random.PRNGKey(2))
    assert (a != c).any()
    # gradients flow through the in-kernel mask (incl. the dlbias kernel
    # and its cross-shard psum)
    g = jax.grad(lambda p: run(jax.random.PRNGKey(1), p).sum())(params)
    assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))


@pytest.mark.slow  # ~36s of per-shard interpret kernels; the helper's
#                  mesh dispatch is covered fast by
#                  test_functional_fused_under_mesh_shard_map and
#                  test_train_step_with_fused_dropout_runs
def test_bart_fused_dropout_trains_deterministically(dp_mesh):
    """bart-test with --dropout-impl fused end-to-end through the model
    apply under a mesh (per-shard interpret kernels on CPU): deterministic
    per key, grads finite."""
    from distributed_llms_example_tpu.models.registry import BART_CONFIGS
    from distributed_llms_example_tpu.models.bart import BartForConditionalGeneration
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    cfg = BART_CONFIGS["bart-test"]
    model = BartForConditionalGeneration(cfg)
    ids = jnp.ones((8, 128), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, None, ids)["params"]
    prev = default_impl()
    try:
        set_default_impl("fused")

        def run(key, p=params):
            with activation_mesh(dp_mesh):
                return model.apply(
                    {"params": p}, ids, None, ids,
                    deterministic=False, rngs={"dropout": key},
                )

        a, b = run(jax.random.PRNGKey(1)), run(jax.random.PRNGKey(1))
        assert (a == b).all()
        assert (a != run(jax.random.PRNGKey(2))).any()
        g = jax.grad(lambda p: run(jax.random.PRNGKey(1), p).sum())(params)
        assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))
    finally:
        set_default_impl(prev)


def test_llama_attn_only_dropout_fires():
    """attn_dropout_rate alone (the dropout-free architecture's recipe
    knob) must actually drop: probs dropout through MultiHeadAttention,
    and the Trainer's rng-threading gate must see it."""
    import dataclasses

    from distributed_llms_example_tpu.models.llama import LlamaForCausalLM
    from distributed_llms_example_tpu.models.registry import LLAMA_CONFIGS

    cfg = dataclasses.replace(LLAMA_CONFIGS["llama-test"], attn_dropout_rate=0.3)
    assert cfg.dropout_rate == 0.0  # the silent-no-op regression scenario
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def run(key=None):
        if key is None:
            return model.apply({"params": params}, ids)
        return model.apply(
            {"params": params}, ids, deterministic=False,
            rngs={"dropout": key},
        )

    det = run()
    a = run(jax.random.PRNGKey(1))
    assert (a == run(jax.random.PRNGKey(1))).all()
    assert (a != det).any()  # dropout actually fired
    assert (a != run(jax.random.PRNGKey(2))).any()
    # the trainer gate threads the rng for attn-only dropout
    attn_only = float(getattr(cfg, "attn_dropout_rate", 0.0) or 0.0) > 0.0
    assert cfg.dropout_rate > 0.0 or attn_only


@pytest.mark.slow  # ~21s train-step compile: slow tier (kernel parity
# and the xla-impl step stay fast)
def test_train_step_with_fused_dropout_runs():
    """make_train_step with dropout rng + --dropout-impl fused: one full
    optimizer step on the CPU mesh, finite loss/grad-norm, and a second
    step with the same key reproduces the first step's loss."""
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.train.optim import make_optimizer
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("bart-test")
    mesh = build_mesh(MeshConfig(data=-1))
    tx, schedule = make_optimizer(learning_rate=1e-4, warmup_steps=0, total_steps=10)
    params = lm.init_params(0)
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    b = {
        "input_ids": np.ones((8, 128), np.int32),
        "attention_mask": np.ones((8, 128), np.int32),
        "labels": np.where(np.arange(128) < 100, 2, LABEL_PAD)[None].repeat(8, 0).astype(np.int32),
    }
    gb = put_batch(b, mesh)
    prev = default_impl()
    try:
        set_default_impl("fused")
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh, with_dropout=True
        )
        step_fn, _ = build(state)
        key = jax.random.PRNGKey(3)
        new_state, metrics = step_fn(state, gb, key)
        loss1 = float(metrics["loss"])
        assert np.isfinite(loss1) and np.isfinite(float(metrics["grad_norm"]))
    finally:
        set_default_impl(prev)
