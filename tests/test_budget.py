"""Step-time budget accounting + unified trace export (ISSUE 9).

Pins: the additive budget account on a fake-clock span recorder
(components sum to wall, the unattributed remainder is the measured
residue); the off-cadence host-blocking-dispatch tripwire; the
zero-new-syncs-off-cadence property of the budget probe (counting-leaf,
same technique as PR 3's health pin); schema round-trip through
obs/report.py's loader for every new event type (``step_budget``,
``trace_spans``, ``serve_request``); the report's "Where did the time
go" section + the --strict dispatch-efficiency floor; and the 2-process
merged-trace golden test (hand-built rank streams with shifted clocks →
one Perfetto-loadable JSON whose events interleave on the shared step
timeline).
"""

from __future__ import annotations

import json
import os

import pytest

from distributed_llms_example_tpu.core.config import TrainConfig
from distributed_llms_example_tpu.obs import TrainerObs, sink as sink_mod
from distributed_llms_example_tpu.obs.budget import (
    COMPONENTS,
    BudgetAccountant,
    aggregate_accounts,
    budget_enabled,
)
from distributed_llms_example_tpu.obs.report import (
    build_report,
    load_jsonl,
    render_markdown,
)
from distributed_llms_example_tpu.obs.spans import SpanRecorder
from distributed_llms_example_tpu.obs.trace import (
    TraceCollector,
    build_trace,
    export_chrome_trace,
    rank_offsets,
)


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# the additive account on a fake clock
# ---------------------------------------------------------------------------


def _drive_step(rec, clock, *, data_wait=0.0, host=0.0, dispatch=0.0,
                busy=0.0, sync=0.0, untracked=0.0):
    if data_wait:
        with rec.span("data_wait"):
            clock.advance(data_wait)
    if host:
        with rec.span("host_overhead"):
            clock.advance(host)
    if dispatch:
        with rec.span("step_dispatch"):
            clock.advance(dispatch)
    if busy:
        with rec.span("device_busy"):
            clock.advance(busy)
    if sync:
        with rec.span("device_sync"):
            clock.advance(sync)
    clock.advance(untracked)
    rec.step_complete()


def test_budget_additivity_on_fake_clock():
    """Hand-driven window: every component lands in its slot, the named
    components plus the unattributed remainder sum EXACTLY to the
    measured wall, and dispatch_efficiency is the documented formula."""
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    bud = BudgetAccountant(rec)
    # three steps: 0.02 data_wait + 0.01 host + 0.005 dispatch + 0.06
    # untracked-free device overlap... last step carries the probe + sync
    for _ in range(2):
        _drive_step(rec, clock, data_wait=0.02, host=0.01, dispatch=0.005,
                    untracked=0.065)
    _drive_step(rec, clock, data_wait=0.02, host=0.01, dispatch=0.005,
                busy=0.05, sync=0.01, untracked=0.005)
    acct = bud.close_window(step=3, epoch=0, emit=False)
    assert acct["event"] == "step_budget" and acct["window_steps"] == 3
    assert acct["data_wait_ms"] == pytest.approx(60.0)
    assert acct["host_overhead_ms"] == pytest.approx(30.0)
    assert acct["dispatch_ms"] == pytest.approx(15.0)
    assert acct["device_busy_ms"] == pytest.approx(50.0)
    assert acct["sync_block_ms"] == pytest.approx(10.0)
    assert acct["unattributed_ms"] == pytest.approx(135.0)
    # additivity: named components + remainder == wall, exactly
    total = sum(acct[f"{c}_ms"] for c in COMPONENTS)
    assert total == pytest.approx(acct["wall_ms"])
    assert acct["wall_ms"] == pytest.approx(300.0)
    assert acct["accounted_frac"] == pytest.approx(165.0 / 300.0, abs=1e-3)
    assert acct["additivity_ok"] is False  # 45% unattributed > 5%
    # efficiency = 1 - (data_wait + host + unattributed)/wall
    assert acct["dispatch_efficiency"] == pytest.approx(
        1 - (60 + 30 + 135) / 300.0, abs=1e-3
    )
    # the window is consumed with summary(), like the cadence does
    rec.summary()
    assert bud.close_window(step=3, emit=False) is None


def test_budget_nested_spans_do_not_double_count():
    """Only OUTERMOST spans enter the per-step partition — a nested span
    would charge the same wall twice and break additivity."""
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    bud = BudgetAccountant(rec)
    with rec.span("step_dispatch"):
        with rec.span("data_wait"):  # nested: window aggregate only
            clock.advance(0.1)
        clock.advance(0.1)
    rec.step_complete()
    acct = bud.close_window(step=1, emit=False)
    assert acct["dispatch_ms"] == pytest.approx(200.0)
    assert acct["data_wait_ms"] == 0.0
    assert acct["unattributed_ms"] == pytest.approx(0.0, abs=1e-6)
    # ...while the span SUMMARY still reports the nesting (existing contract)
    assert rec.summary()["spans"]["data_wait"]["total_ms"] == pytest.approx(100.0)


def test_budget_mark_step_start_excludes_between_step_work():
    """Checkpoint/eval time between steps is excluded from the next
    step's duration (mark_step_start) — the budget partition must drop
    those spans too, or components would exceed wall."""
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    bud = BudgetAccountant(rec)
    _drive_step(rec, clock, dispatch=0.1)
    with rec.span("checkpoint"):
        clock.advance(5.0)
    rec.mark_step_start()
    _drive_step(rec, clock, dispatch=0.1)
    acct = bud.close_window(step=2, emit=False)
    assert acct["wall_ms"] == pytest.approx(200.0)
    assert acct["host_overhead_ms"] == 0.0  # the 5 s checkpoint dropped
    assert acct["dispatch_ms"] == pytest.approx(200.0)


def test_budget_offcadence_tripwire():
    """A NON-cadence step whose dispatch eats a device-step's worth of
    wall is a host-blocked transfer (the runtime twin of repo-lint rule
    4): counted and flagged.  A healthy async window — millisecond
    dispatches, the cadence step carrying the block — stays quiet."""
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    # warmup_windows=0: evaluate the detector on every window (the
    # default 1 stands down for the compile window — tested below)
    bud = BudgetAccountant(rec, warmup_windows=0)
    # healthy: 3 fast dispatches, the cadence (last) step drains 0.27s
    for _ in range(3):
        _drive_step(rec, clock, dispatch=0.002, untracked=0.002)
    _drive_step(rec, clock, dispatch=0.002, busy=0.27, sync=0.01)
    acct = bud.close_window(step=4, emit=False)
    assert acct["offcadence_sync_steps"] == 0
    assert acct["offcadence_sync_suspect"] is False
    rec.summary()
    # lock-stepped: every dispatch blocks ~a full device step
    for _ in range(3):
        _drive_step(rec, clock, dispatch=0.07, untracked=0.001)
    _drive_step(rec, clock, dispatch=0.07, sync=0.001)  # nothing to drain
    acct = bud.close_window(step=8, emit=False)
    assert acct["offcadence_sync_steps"] == 3  # every non-cadence step
    assert acct["offcadence_sync_suspect"] is True


def test_budget_tripwire_warmup_window_stands_down():
    """The FIRST window holds the JIT compile — a legitimate dispatch
    block the tripwire cannot tell from a host-blocking transfer, so the
    default warmup suppresses it (stamped, not silent) and the detector
    arms from window 2."""
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    bud = BudgetAccountant(rec)  # default warmup_windows=1
    _drive_step(rec, clock, dispatch=15.0)  # the compile step
    _drive_step(rec, clock, dispatch=0.002, busy=0.1)
    acct = bud.close_window(step=2, emit=False)
    assert acct["warmup"] is True
    assert acct["offcadence_sync_suspect"] is False
    rec.summary()
    # window 2: the same fat dispatch now IS a finding
    _drive_step(rec, clock, dispatch=0.08, untracked=0.001)
    _drive_step(rec, clock, dispatch=0.002, sync=0.001)
    acct = bud.close_window(step=4, emit=False)
    assert "warmup" not in acct
    assert acct["offcadence_sync_suspect"] is True


def test_budget_probe_zero_syncs_off_cadence(tmp_path):
    """The counting-leaf pin (PR 3's technique): the budget layer's only
    device interaction is the cadenced probe — off-cadence steps cost
    zero blocks, the cadence step exactly one."""

    class CountingLeaf:
        blocks = 0

        def block_until_ready(self):
            CountingLeaf.blocks += 1
            return self

    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", log_every_steps=4,
        health="off",
    )
    obs = TrainerObs(cfg, start_step=0)
    assert obs.budget is not None
    CountingLeaf.blocks = 0
    for step in (1, 2, 3):
        with obs.step_span():
            pass
        obs.budget_probe(step, CountingLeaf())
        obs.on_step(step, 0, {})
        assert CountingLeaf.blocks == 0  # the invariant
    with obs.step_span():
        pass
    obs.budget_probe(4, CountingLeaf())
    obs.on_step(4, 0, {})
    assert CountingLeaf.blocks == 1  # exactly the cadence probe
    assert obs.budget.history, "cadence must close a step_budget account"
    acct = obs.budget.history[-1]
    assert acct["window_steps"] == 4
    assert acct["device_busy_ms"] >= 0.0
    sink_mod.current_sink().close()


def test_budget_window_resets_without_obs_window(tmp_path):
    """--obs off --obs-budget on: emit_window (which resets the span
    window) never runs, so the cadence must consume the window itself —
    otherwise every account re-counts all prior steps (regression)."""
    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="off", obs_budget="on",
        log_every_steps=2, health="off",
    )
    obs = TrainerObs(cfg, start_step=0)
    assert obs.budget is not None and not obs.enabled
    for step in range(1, 7):
        with obs.step_span():
            pass
        obs.on_step(step, 0, {})
    assert [a["window_steps"] for a in obs.budget.history] == [2, 2, 2]


def test_budget_enabled_tristate():
    assert budget_enabled(TrainConfig(obs_budget="on", obs="off"))
    assert not budget_enabled(TrainConfig(obs_budget="off", obs="jsonl"))
    assert budget_enabled(TrainConfig(obs_budget="auto", obs="stdout"))
    assert budget_enabled(TrainConfig(obs_budget="auto", obs="jsonl"))
    assert not budget_enabled(TrainConfig(obs_budget="auto", obs="off"))


def test_aggregate_accounts_weighted():
    a = {
        "wall_ms": 100.0, "window_steps": 2, "dispatch_efficiency": 1.0,
        **{f"{c}_ms": 0.0 for c in COMPONENTS},
    }
    b = {
        "wall_ms": 300.0, "window_steps": 6, "dispatch_efficiency": 0.5,
        **{f"{c}_ms": 10.0 for c in COMPONENTS},
        "offcadence_sync_steps": 2,
    }
    agg = aggregate_accounts([a, b])
    assert agg["windows"] == 2 and agg["steps"] == 8
    assert agg["wall_ms"] == pytest.approx(400.0)
    # wall-weighted: (1.0·100 + 0.5·300) / 400
    assert agg["dispatch_efficiency"] == pytest.approx(0.625)
    assert agg["unattributed_ms"] == pytest.approx(10.0)
    assert agg["offcadence_sync_steps"] == 2
    assert aggregate_accounts([]) is None


# ---------------------------------------------------------------------------
# trace collection + the bulk sink gate
# ---------------------------------------------------------------------------


def test_trace_collector_flush_is_file_only(tmp_path, capsys):
    path = str(tmp_path / "obs" / "metrics-p000.jsonl")
    sink_mod.install_sink(
        sink_mod.TeeSink([sink_mod.StdoutSink(), sink_mod.JsonlFileSink(path)])
    )
    clock = FakeClock()
    col = TraceCollector(clock=clock)
    clock.advance(1.0)
    col.on_span("step_dispatch", clock.t - 0.5, 0.5)
    col.note_step(1)
    col.flush(1)
    sink_mod.current_sink().close()
    # bulk records never hit the stdout platform channel...
    assert capsys.readouterr().out == ""
    # ...but land schema-stamped in the per-process file
    recs, errs = load_jsonl(path)
    assert errs == []
    rec = next(r for r in recs if r.get("event") == "trace_spans")
    assert rec["spans"] == [["step_dispatch", 0.5, 0.5]]
    assert rec["steps"] == [[1, 1.0]]
    # empty flush emits nothing
    col.flush(2)


def test_trace_collector_bounded_with_drop_count(tmp_path):
    path = str(tmp_path / "obs" / "m.jsonl")
    sink_mod.install_sink(sink_mod.JsonlFileSink(path))
    col = TraceCollector(clock=FakeClock(), max_spans=4)
    for i in range(10):
        col.on_span("s", float(i), 0.1)
    col.flush(1)
    sink_mod.current_sink().close()
    rec = next(r for r in load_jsonl(path)[0] if r.get("event") == "trace_spans")
    assert len(rec["spans"]) == 4
    assert rec["dropped_spans"] == 6  # truncation is counted, not silent


# ---------------------------------------------------------------------------
# schema round-trip: every new event type through the report loader
# ---------------------------------------------------------------------------


def test_schema_round_trip_new_event_types(tmp_path):
    """step_budget, trace_spans and serve_request all parse back through
    obs/report.py's loader schema-checked, feed build_report, and the
    markdown renders the budget section."""
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", log_every_steps=2,
        health="off",
    )
    obs = TrainerObs(cfg, start_step=0)
    assert obs.budget is not None and obs.trace is not None
    for step in (1, 2):
        with obs.host_span():
            pass
        with obs.step_span():
            pass
        with obs.sync_span():
            pass
        obs.on_step(step, 0, {})
    # a serving request span, the shape the engine emits
    log_json({
        "event": "serve_request", "request": 0, "slot": 1,
        "queue_wait_ms": 1.5, "prefill_ms": 20.0, "ttft_ms": 30.0,
        "decode_ms": 55.0, "tokens": 12, "t_admit_s": 0.0015,
        "t_done_s": 0.085, "finished_at_step": 12,
    })
    obs.finalize(2, 0)
    sink_mod.current_sink().close()
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records, errors = load_jsonl(path)
    assert errors == []
    events = {r.get("event", "metric") for r in records}
    assert {"step_budget", "trace_spans", "serve_request"} <= events
    budget = next(r for r in records if r.get("event") == "step_budget")
    for c in COMPONENTS:
        assert f"{c}_ms" in budget
    assert {"dispatch_efficiency", "accounted_frac", "additivity_ok",
            "offcadence_sync_steps"} <= set(budget)
    report = build_report(str(tmp_path))
    assert report["schema_errors"] == []
    assert report["budget"] is not None
    assert report["budget"]["ranks"]["0"]["windows"] >= 1
    md = render_markdown(report)
    assert "Where did the time go" in md
    assert "dispatch efficiency" in md


# ---------------------------------------------------------------------------
# report: budget section, offenders, incidents, the strict floor
# ---------------------------------------------------------------------------


def _stamp(rec: dict) -> dict:
    return {"schema_version": 1, **rec}


def _budget_event(step, *, wall=1000.0, data_wait=300.0, dispatch=50.0,
                  busy=500.0, sync=50.0, host=50.0, unattr=50.0,
                  eff=None, offcadence=0):
    eff = eff if eff is not None else round(
        1 - (data_wait + host + unattr) / wall, 4
    )
    return _stamp({
        "event": "step_budget", "step": step, "window_steps": 4,
        "wall_ms": wall, "data_wait_ms": data_wait, "dispatch_ms": dispatch,
        "device_busy_ms": busy, "sync_block_ms": sync,
        "host_overhead_ms": host, "unattributed_ms": unattr,
        "accounted_frac": round((wall - unattr) / wall, 4),
        "additivity_ok": unattr <= 0.05 * wall,
        "dispatch_efficiency": eff,
        "offcadence_sync_steps": offcadence,
        "offcadence_sync_suspect": offcadence > 0,
    })


def _write_rank(tmp_path, rank: int, recs: list[dict]) -> None:
    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir, exist_ok=True)
    with open(obs_dir / f"metrics-p{rank:03d}.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_report_budget_section_and_strict_floor(tmp_path, capsys):
    from distributed_llms_example_tpu.obs.report import main as report_main

    _write_rank(tmp_path, 0, [
        _budget_event(2),
        _budget_event(4, data_wait=600.0, unattr=100.0, busy=200.0,
                      offcadence=3),
    ])
    _write_rank(tmp_path, 1, [_budget_event(2), _budget_event(4)])
    report = build_report(str(tmp_path))
    budget = report["budget"]
    assert set(budget["ranks"]) == {"0", "1"}
    # rank 0: (0.6·1000 + 0.25·1000)/2000 wall-weighted
    assert budget["ranks"]["0"]["dispatch_efficiency"] == pytest.approx(
        0.425, abs=1e-3
    )
    assert budget["dispatch_efficiency"] == pytest.approx(
        (0.425 * 2000 + 0.6 * 2000) / 4000, abs=1e-3
    )
    # worst offender: data_wait dominates the stall components
    assert budget["offenders"][0]["component"] == "data_wait"
    assert budget["incidents"] == [{
        "rank": 0, "step": 4, "blocked_steps": 3, "window_steps": 4,
        "dispatch_ms": 50.0,
    }]
    md = render_markdown(report)
    assert "off-cadence host-blocking dispatch incidents" in md
    assert "rank 0 window@step 4: 3/4 step(s)" in md
    # the strict floor: 0.52 mean efficiency fails a 0.9 floor...
    rc = report_main([
        str(tmp_path), "--strict", "--min-dispatch-efficiency", "0.9",
    ])
    assert rc == 1
    assert "below the 0.9 floor" in capsys.readouterr().err
    # ...passes a 0.4 floor, and no floor means no budget gate at all
    assert report_main([
        str(tmp_path), "--strict", "--min-dispatch-efficiency", "0.4",
    ]) == 0
    assert report_main([str(tmp_path), "--strict"]) == 0


def test_report_strict_floor_without_budget_records(tmp_path, capsys):
    from distributed_llms_example_tpu.obs.report import main as report_main

    _write_rank(tmp_path, 0, [_stamp({"step": 1, "loss": 1.0})])
    assert report_main([str(tmp_path)]) == 0
    rc = report_main([
        str(tmp_path), "--strict", "--min-dispatch-efficiency", "0.5",
    ])
    assert rc == 1  # a floor with no data is a failed gate, not a pass
    assert "no step_budget records" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the merged cross-host trace: golden 2-process alignment + Perfetto shape
# ---------------------------------------------------------------------------


def test_rank_offsets_alignment_and_fallback():
    # shared steps: rank 1's clock runs 5.0 s ahead → offset −5.0
    marks = {0: {1: 1.0, 2: 2.0, 3: 3.0}, 1: {1: 6.0, 2: 7.0, 3: 8.1}}
    offs = rank_offsets(marks, {})
    assert offs[0] == 0.0
    assert offs[1] == pytest.approx(-5.0)  # median is robust to the 8.1
    # no shared steps: NTP wall-clock fallback (wall0[r] − wall0[base])
    offs = rank_offsets(
        {0: {1: 1.0}, 1: {9: 1.0}}, {0: 1000.0, 1: 1002.5}
    )
    assert offs[1] == pytest.approx(2.5)
    # nothing to go on: identity
    assert rank_offsets({0: {1: 1.0}, 1: {}}, {})[1] == 0.0


def _trace_rank(rank: int, shift: float) -> list[dict]:
    """One rank's stream: two steps, spans inside each, clocks shifted by
    ``shift`` (each host's perf_counter epoch is arbitrary)."""
    return [
        _stamp({
            "event": "trace_spans", "step": 2, "wall0": 1000.0 + shift,
            "spans": [
                ["data_wait", 0.00 + shift, 0.10],
                ["step_dispatch", 0.10 + shift, 0.80],
                ["device_sync", 1.90 + shift, 0.05],
            ],
            "steps": [[1, 1.00 + shift], [2, 2.00 + shift]],
        }),
        _stamp({
            "event": "step_budget", "step": 2, "window_steps": 2,
            "wall_ms": 2000.0, "dispatch_efficiency": 0.9,
        }),
    ]


def test_two_process_merged_trace_golden(tmp_path):
    """Two hand-built rank streams with clocks 7 s apart merge into ONE
    Chrome-trace JSON: valid Perfetto shape, both pids present, and the
    ranks' spans INTERLEAVE on the shared step timeline after the
    step-boundary alignment (the acceptance criterion)."""
    _write_rank(tmp_path, 0, _trace_rank(0, 0.0))
    _write_rank(tmp_path, 1, _trace_rank(1, 7.0))
    out = tmp_path / "trace.json"
    summary = export_chrome_trace(str(tmp_path), str(out))
    assert summary["ranks"] == [0, 1]
    trace = json.loads(open(out).read())
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    assert trace["displayTimeUnit"] == "ms"
    slices = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in slices} == {0, 1}
    # alignment: the same span on both ranks lands at the same ts (the
    # 7 s clock shift is gone), so the two ranks' events interleave
    by_rank = {
        pid: sorted(
            e["ts"] for e in slices if e["pid"] == pid and e["name"] == "step_dispatch"
        )
        for pid in (0, 1)
    }
    assert by_rank[0] == pytest.approx(by_rank[1], abs=1e3)  # within 1 ms
    # both ranks' dispatch spans sit INSIDE the merged step-1 window
    r0_steps = [e for e in events if e["pid"] == 0 and e.get("ph") == "X"
                and e["name"].startswith("step ")]
    assert r0_steps, "step-boundary slices must be rendered"
    lo = min(e["ts"] for e in r0_steps)
    hi = max(e["ts"] + e["dur"] for e in r0_steps)
    for pid in (0, 1):
        sync = next(e for e in slices if e["pid"] == pid and e["name"] == "device_sync")
        assert lo <= sync["ts"] <= hi
    # budget counters ride the trace as Perfetto counter tracks
    counters = [e for e in events if e.get("ph") == "C"]
    assert {c["pid"] for c in counters} == {0, 1}
    assert all(
        c["args"]["dispatch_efficiency"] == 0.9 for c in counters
    )


def test_trace_includes_serving_request_lifecycles(tmp_path):
    _write_rank(tmp_path, 0, [
        _stamp({
            "event": "serve_request", "request": 3, "slot": 2,
            "queue_wait_ms": 100.0, "prefill_ms": 50.0, "ttft_ms": 160.0,
            "decode_ms": 400.0, "tokens": 9, "t_admit_s": 0.1,
            "t_done_s": 0.55, "finished_at_step": 40,
        }),
    ])
    trace = build_trace(str(tmp_path))
    names = [e.get("name", "") for e in trace["traceEvents"]]
    assert any("req 3 queue" in n for n in names)
    assert any("req 3 prefill" in n for n in names)
    assert any("req 3 decode" in n for n in names)
    q = next(e for e in trace["traceEvents"] if e.get("name") == "req 3 queue")
    p = next(e for e in trace["traceEvents"] if e.get("name") == "req 3 prefill")
    # the queue slice ends where prefill begins
    assert q["ts"] + q["dur"] == pytest.approx(p["ts"], abs=1.0)


# ---------------------------------------------------------------------------
# the cadenced optimizer-apply gauge (ISSUE 10 satellite) + the gate script
# ---------------------------------------------------------------------------


def test_probe_optimizer_gauge_lands_on_account():
    """probe_optimizer: the first call warms (a lazily-built probe
    jit-compiles inside fn — a compile is not an apply), subsequent calls
    time fn and the newest sample rides the next account as
    optimizer_apply_ms + optimizer_share_of_step."""
    import jax.numpy as jnp

    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    bud = BudgetAccountant(rec)
    calls = []

    def fn():
        # "compile" costs 1.0s, later applies 0.007s — the fake clock
        # advances inside the timed region exactly like a real block
        calls.append(1)
        clock.advance(1.0 if len(calls) == 1 else 0.007)
        return jnp.zeros(())

    _drive_step(rec, clock, dispatch=0.05, untracked=0.05)
    bud.probe_optimizer(fn)
    # warm + timed: two calls, and the SAMPLE is the second (7 ms)
    assert len(calls) == 2
    acct = bud.close_window(step=1, emit=False)
    assert acct["optimizer_apply_ms"] == pytest.approx(7.0)
    # share: 7 ms of a 100 ms mean step wall
    assert acct["optimizer_share_of_step"] == pytest.approx(0.07, abs=1e-3)
    # next window: one timed call only, sample refreshed
    _drive_step(rec, clock, dispatch=0.05, untracked=0.05)
    bud.probe_optimizer(fn)
    assert len(calls) == 3
    acct = bud.close_window(step=2, emit=False)
    assert acct["optimizer_apply_ms"] == pytest.approx(7.0)


def test_trainer_obs_optimizer_probe_cadence_gated(tmp_path):
    """TrainerObs.optimizer_probe runs the factory at the log cadence
    only — off-cadence steps never touch it (zero new syncs)."""
    import jax.numpy as jnp

    cfg = TrainConfig(output_dir=str(tmp_path), obs="off", obs_budget="on",
                      log_every_steps=3, health="off")
    obs = TrainerObs(cfg, start_step=0)
    calls = []

    def fn():
        calls.append(1)
        return jnp.zeros(())

    for step in range(1, 7):
        with obs.step_span():
            pass
        obs.optimizer_probe(step, fn)
        obs.on_step(step, 0, {})
    # cadence steps 3 and 6: warm+timed at 3, timed at 6
    assert len(calls) == 3
    assert obs.budget.history[-1].get("optimizer_apply_ms") is not None
    sink_mod.current_sink().close()


def test_aggregate_accounts_carries_optimizer_gauge():
    base = {
        "wall_ms": 100.0, "window_steps": 2, "dispatch_efficiency": 1.0,
        **{f"{c}_ms": 0.0 for c in COMPONENTS},
    }
    a = dict(base, optimizer_apply_ms=10.0, optimizer_share_of_step=0.2)
    b = dict(base, optimizer_apply_ms=20.0, optimizer_share_of_step=0.4)
    c = dict(base)  # a window without a sample must not poison the mean
    agg = aggregate_accounts([a, b, c])
    assert agg["optimizer_apply_ms"] == pytest.approx(15.0)
    assert agg["optimizer_share_of_step"] == pytest.approx(0.3)
    assert "optimizer_apply_ms" not in (aggregate_accounts([c]) or {})


def test_obs_gate_script(tmp_path, capsys):
    """scripts/obs_gate.py: the pinned-flags wrapper fails a run whose
    wall-weighted dispatch_efficiency sits under the floor, passes one
    above it, and fails when NO step_budget records exist (a missing
    measurement is never a pass)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_gate",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "obs_gate.py"),
    )
    obs_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_gate)

    good = tmp_path / "good"
    _write_rank(good, 0, [_budget_event(2, eff=0.97), _budget_event(4, eff=0.95)])
    assert obs_gate.main([str(good)]) == 0

    bad = tmp_path / "bad"
    _write_rank(bad, 0, [_budget_event(2, eff=0.5)])
    assert obs_gate.main([str(bad)]) == 1
    assert obs_gate.main([str(bad), "--min-dispatch-efficiency", "0.4"]) == 0

    empty = tmp_path / "empty"
    _write_rank(empty, 0, [_stamp({"step": 1, "loss": 1.0})])
    assert obs_gate.main([str(empty)]) == 1
    capsys.readouterr()


def test_report_renders_optimizer_gauge(tmp_path):
    ev = _budget_event(2)
    ev["optimizer_apply_ms"] = 12.5
    ev["optimizer_share_of_step"] = 0.05
    _write_rank(tmp_path, 0, [ev])
    report = build_report(str(tmp_path))
    assert report["budget"]["ranks"]["0"]["optimizer_apply_ms"] == pytest.approx(12.5)
    md = render_markdown(report)
    assert "optimizer apply (cadenced stand-alone sample)" in md
    # absent gauge → no line (and no crash)
    plain = tmp_path / "plain"
    _write_rank(plain, 0, [_budget_event(2)])
    assert "optimizer apply (cadenced" not in render_markdown(build_report(str(plain)))


def test_probe_optimizer_failure_disables_gauge_not_run(capsys):
    """A failing probe (OOM compiling the stand-alone apply, transient
    backend error) must disable the gauge with one logged event — never
    propagate into the training loop — and a failed WARM call must not
    leave a later compile mislabeled as the timed sample."""
    import jax.numpy as jnp

    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    bud = BudgetAccountant(rec)
    calls = []

    def failing_then_fine():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: probe compile OOM")
        clock.advance(0.007)
        return jnp.zeros(())

    _drive_step(rec, clock, dispatch=0.05, untracked=0.05)
    bud.probe_optimizer(failing_then_fine)  # swallowed, probe disabled
    assert len(calls) == 1
    bud.probe_optimizer(failing_then_fine)  # dead: never calls fn again
    assert len(calls) == 1
    acct = bud.close_window(step=1, emit=False)
    assert "optimizer_apply_ms" not in acct
    assert "optimizer_probe_disabled" in capsys.readouterr().out
