"""Mesh construction + multi-host fact resolution tests."""

import os

import jax
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import (
    _valohai_facts,
    build_mesh,
    device_report,
    resolve_mesh_shape,
)


def test_resolve_wildcard():
    spec = resolve_mesh_shape(MeshConfig(data=-1, fsdp=2, tensor=2), 8)
    assert spec.as_tuple() == (1, 2, 2, 1, 1, 2)
    assert spec.size == 8
    assert spec.batch_shards == 4


def test_resolve_exact():
    spec = resolve_mesh_shape(MeshConfig(data=8, fsdp=1), 8)
    assert spec.as_tuple() == (1, 8, 1, 1, 1, 1)


def test_resolve_errors():
    with pytest.raises(ValueError):
        resolve_mesh_shape(MeshConfig(data=3, fsdp=2), 8)  # 6 != 8
    with pytest.raises(ValueError):
        resolve_mesh_shape(MeshConfig(data=-1, fsdp=3), 8)  # 8 % 3 != 0
    with pytest.raises(ValueError, match="positive"):
        resolve_mesh_shape(MeshConfig(data=-1, fsdp=0), 8)  # zero axis


def test_build_mesh_axes(mesh8):
    assert mesh8.axis_names == ("stage", "data", "fsdp", "expert", "sequence", "tensor")
    assert mesh8.devices.size == 8


def test_valohai_facts_from_env(monkeypatch):
    monkeypatch.setenv("VH_MASTER_IP", "10.0.0.7")
    monkeypatch.setenv("VH_WORLD_SIZE", "4")
    monkeypatch.setenv("VH_RANK", "2")
    assert _valohai_facts() == ("10.0.0.7", 4, 2)


def test_valohai_facts_torchrun_compat(monkeypatch):
    for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.9")
    monkeypatch.setenv("WORLD_SIZE", "2")
    monkeypatch.setenv("RANK", "1")
    assert _valohai_facts() == ("10.0.0.9", 2, 1)


def test_valohai_facts_local_fallback(monkeypatch):
    for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK", "MASTER_ADDR", "WORLD_SIZE", "RANK"):
        monkeypatch.delenv(k, raising=False)
    ip, world, rank = _valohai_facts()
    assert world == 1 and rank is None


def test_initialize_distributed_refuses_partial_facts(monkeypatch):
    from distributed_llms_example_tpu.core.mesh import initialize_distributed

    for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK", "MASTER_ADDR", "WORLD_SIZE", "RANK"):
        monkeypatch.delenv(k, raising=False)
    # multi-process without a coordinator must raise, not silently degrade
    with pytest.raises(ValueError, match="coordinator"):
        initialize_distributed(num_processes=4, process_id=1)
    # multi-process without a rank must raise too
    with pytest.raises(ValueError, match="process id"):
        initialize_distributed(coordinator_address="10.0.0.1", num_processes=4)
    # world size 1 is the local fallback: no error, no init
    initialize_distributed(num_processes=1)


def test_device_report():
    rep = device_report()
    assert rep["global_device_count"] == jax.device_count()
    assert rep["backend"] == "cpu"
    assert len(rep["devices"]) >= 1


def test_mesh_config_parse():
    from distributed_llms_example_tpu.core.config import parse_mesh_arg

    cfg = parse_mesh_arg("data=2,fsdp=4")
    assert cfg.data == 2 and cfg.fsdp == 4 and cfg.tensor == 1
    cfg = parse_mesh_arg("")
    assert cfg.data == -1
    # wildcard on a non-data axis must not collide with data's default -1
    cfg = parse_mesh_arg("tensor=-1")
    assert cfg.data == 1 and cfg.tensor == -1
    with pytest.raises(ValueError):
        parse_mesh_arg("bogus=2")
