"""Model-level flash-vs-XLA attention parity (forward + gradients).

Forces ``attention_impl='flash'`` (interpreted Pallas on CPU) on the tiny
BART and LLaMA configs and checks logits/grads against the XLA path — the
guarantee that flipping the kernel on TPU cannot change training numerics.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.models.bart import BartForConditionalGeneration
from distributed_llms_example_tpu.models.llama import LlamaForCausalLM
from distributed_llms_example_tpu.models.registry import BART_CONFIGS, LLAMA_CONFIGS


def _variants(cfg, module_cls):
    mods = {}
    for impl in ("xla", "flash"):
        mods[impl] = module_cls(dataclasses.replace(cfg, attention_impl=impl))
    return mods


@pytest.mark.slow  # ~9s dual-impl compile: slow tier (t5 flash parity
# stays fast)
def test_llama_flash_matches_xla():
    cfg = LLAMA_CONFIGS["llama-test"]  # head_dim 16
    mods = _variants(cfg, LlamaForCausalLM)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 64)), jnp.int32)
    mask = jnp.ones((2, 64), jnp.int32).at[0, 50:].set(0)
    params = mods["xla"].init(jax.random.PRNGKey(0), ids, mask)["params"]

    def loss(m):
        def f(p):
            logits = m.apply({"params": p}, ids, mask)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        return jax.value_and_grad(f)(params)

    (l_x, g_x), (l_f, g_f) = loss(mods["xla"]), loss(mods["flash"])
    np.testing.assert_allclose(float(l_x), float(l_f), rtol=1e-5)
    flat_x, flat_f = jax.tree.leaves(g_x), jax.tree.leaves(g_f)
    for a, b in zip(flat_x, flat_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3)


def test_bart_flash_matches_xla():
    cfg = BART_CONFIGS["bart-test"]  # head_dim 16
    mods = _variants(cfg, BartForConditionalGeneration)
    rng = np.random.RandomState(1)
    src = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 64)), jnp.int32)
    src_mask = jnp.ones((2, 64), jnp.int32).at[1, 40:].set(0)
    tgt = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 32)), jnp.int32)
    params = mods["xla"].init(jax.random.PRNGKey(0), src, src_mask, tgt)["params"]

    out_x = mods["xla"].apply({"params": params}, src, src_mask, tgt)
    out_f = mods["flash"].apply({"params": params}, src, src_mask, tgt)
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_f), atol=2e-4, rtol=1e-3
    )


def test_bart_flash_cached_generation_falls_back():
    """attention_impl='flash' must not break cached decode (q_len=1 steps
    silently use the XLA path) and must produce identical greedy tokens."""
    from distributed_llms_example_tpu.evaluation.generation import make_greedy_generate

    cfg = BART_CONFIGS["bart-test"]
    mods = _variants(cfg, BartForConditionalGeneration)
    rng = np.random.RandomState(2)
    src = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 32)), jnp.int32)
    src_mask = jnp.ones((2, 32), jnp.int32).at[0, 20:].set(0)
    params = mods["xla"].init(jax.random.PRNGKey(0), src, src_mask, src[:, :8])["params"]

    toks = {}
    for impl, mod in mods.items():
        gen = make_greedy_generate(mod, dataclasses.replace(cfg, attention_impl=impl), max_new_tokens=12)
        toks[impl] = np.asarray(gen(params, src, src_mask))
    np.testing.assert_array_equal(toks["xla"], toks["flash"])


def test_t5_flash_matches_xla_incl_bias_table_grad():
    """T5 with attention_impl='flash': the learned relative-position bias
    rides the kernel's differentiable learned_bias input — logits AND
    gradients (including the bias tables) must match the XLA path, and the
    table gradients must be nonzero (a silently-constant bias was exactly
    the round-2 failure mode this guards against)."""
    from distributed_llms_example_tpu.models.registry import T5_CONFIGS
    from distributed_llms_example_tpu.models.t5 import T5ForConditionalGeneration

    cfg = dataclasses.replace(T5_CONFIGS["t5-test"], dropout_rate=0.0)
    mods = _variants(cfg, T5ForConditionalGeneration)
    rng = np.random.RandomState(2)
    src = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 64)), jnp.int32)
    src_mask = jnp.ones((2, 64), jnp.int32).at[1, 48:].set(0)
    tgt = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 32)), jnp.int32)
    params = mods["xla"].init(jax.random.PRNGKey(3), src, src_mask, tgt)["params"]

    def loss(m):
        def f(p):
            logits = m.apply({"params": p}, src, src_mask, tgt)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        return jax.value_and_grad(f)(params)

    (l_x, g_x), (l_f, g_f) = loss(mods["xla"]), loss(mods["flash"])
    np.testing.assert_allclose(float(l_x), float(l_f), rtol=1e-5)
    paths_x = jax.tree_util.tree_flatten_with_path(g_x)[0]
    paths_f = jax.tree.leaves(g_f)
    for (path, a), b in zip(paths_x, paths_f):
        name = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3, err_msg=name
        )
        if "relative_attention_bias" in name:
            assert np.abs(np.asarray(b)).sum() > 0, f"{name}: zero bias-table grad"


@pytest.mark.slow  # ~20s sharded-lbias compile: slow tier (single-device
# t5 flash parity incl. the bias-table grad stays fast)
def test_t5_flash_multi_device_bias_table_grads(mesh8):
    """T5 with attention_impl='flash' on an 8-device mesh: self-attention
    takes the sharded learned-bias path (hand-written vjp) — logits and
    grads incl. the relative-position tables match the XLA path."""
    from distributed_llms_example_tpu.models.registry import T5_CONFIGS
    from distributed_llms_example_tpu.models.t5 import T5ForConditionalGeneration
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    cfg = dataclasses.replace(T5_CONFIGS["t5-test"], dropout_rate=0.0)
    mods = _variants(cfg, T5ForConditionalGeneration)
    rng = np.random.RandomState(6)
    src = jnp.asarray(rng.randint(3, cfg.vocab_size, (8, 128)), jnp.int32)
    src_mask = jnp.ones((8, 128), jnp.int32).at[1, 96:].set(0)
    tgt = jnp.asarray(rng.randint(3, cfg.vocab_size, (8, 32)), jnp.int32)
    params = mods["xla"].init(jax.random.PRNGKey(4), src, src_mask, tgt)["params"]

    def loss(m):
        def f(p):
            logits = m.apply({"params": p}, src, src_mask, tgt)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        with activation_mesh(mesh8):
            return jax.jit(jax.value_and_grad(f))(params)

    (l_x, g_x), (l_f, g_f) = loss(mods["xla"]), loss(mods["flash"])
    np.testing.assert_allclose(float(l_x), float(l_f), rtol=1e-5)
    paths_x = jax.tree_util.tree_flatten_with_path(g_x)[0]
    for (path, a), b in zip(paths_x, jax.tree.leaves(g_f)):
        name = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3, err_msg=name
        )
        if "relative_attention_bias" in name:
            assert np.abs(np.asarray(b)).sum() > 0, f"{name}: zero bias-table grad"
