"""Elastic pod-scale training (ISSUE 14): resharding restore +
topology-change recovery.

Acceptance pins: the mesh-layout payload/sidecar round trip; the
metadata-driven resharding restore across ``data×fsdp`` factorizations
on the 8-device mesh (2×4 → 4×2 → 8×1, params BIT-EQUAL, same-mesh
resume untouched); error-feedback re-tile (group sums preserve the
total deferred error) and zero-fill in both directions; the
``lint_reshard_layout`` proof pass green on a supported reshard and
firing on unmappable factorizations (stage/expert moves, unknown axes);
the ``host_loss@K`` chaos grammar + in-process topology-change path
(teardown → rebuild → reshard restore → cursor resume); the
``obs.report`` topology timeline with reshard wall-clock in MTTR and
the injected-vs-organic split ``--strict`` gates on; repo-lint rule 11
(mesh construction / ``jax.distributed`` outside core/mesh.py).

The ROADMAP acceptance run — a 2-process CPU run killed down to 1
process resuming through the resharding restore and matching a clean
1-process run from the same checkpoint (bit-equal final params) — rides
the slow tier next to tests/test_multiprocess.py.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import (
    CheckpointConfig,
    MeshConfig,
    TrainConfig,
)
from distributed_llms_example_tpu.core.mesh import MeshSpec, elastic_mesh_spec
from distributed_llms_example_tpu.io.checkpoint import (
    describe_factorization,
    mesh_layout_array,
    parse_mesh_layout,
)
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.chaos import parse_chaos
from distributed_llms_example_tpu.obs.report import build_report, render_markdown


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


# ---------------------------------------------------------------------------
# mesh-layout payload leaf + elastic mesh resolution
# ---------------------------------------------------------------------------

def test_mesh_layout_leaf_round_trip():
    leaf = mesh_layout_array({"data": 2, "fsdp": 4}, 2, 8)
    parsed = parse_mesh_layout(leaf)
    assert parsed["axes"]["data"] == 2 and parsed["axes"]["fsdp"] == 4
    assert parsed["axes"]["stage"] == 1  # unnamed axes default to 1
    assert parsed["processes"] == 2 and parsed["ef_workers"] == 8
    assert "data=2" in describe_factorization(parsed)
    assert "2 process(es)" in describe_factorization(parsed)
    assert describe_factorization(None) == "<unrecorded>"
    with pytest.raises(ValueError, match="entries"):
        parse_mesh_layout(np.zeros(3, np.int32))


def test_elastic_mesh_spec_rescales_data_axis():
    # a -1 axis absorbs the change exactly as at startup
    spec = elastic_mesh_spec(MeshConfig(data=-1, fsdp=2), 4)
    assert (spec.data, spec.fsdp) == (2, 2)
    # a fully pinned factorization re-scales DATA onto the survivors
    spec = elastic_mesh_spec(MeshConfig(data=2, fsdp=4), 4)
    assert (spec.data, spec.fsdp) == (1, 4)
    # ...and refuses, named, when the model axes no longer fit
    with pytest.raises(ValueError, match="surviving"):
        elastic_mesh_spec(MeshConfig(data=2, fsdp=8), 4)


# ---------------------------------------------------------------------------
# error-feedback re-tile
# ---------------------------------------------------------------------------

def test_retile_error_feedback_preserves_total_residual():
    from distributed_llms_example_tpu.ops.quant_collectives import (
        retile_error_feedback,
    )

    rng = np.random.RandomState(0)
    ef = {"w": rng.randn(4, 3, 2).astype(np.float32),
          "b": rng.randn(4, 5).astype(np.float32)}
    out = retile_error_feedback(ef, 2)
    assert {k: v.shape for k, v in out.items()} == {"w": (2, 3, 2), "b": (2, 5)}
    for k in ef:
        # each new group = sum of the old groups it merges...
        np.testing.assert_allclose(
            np.asarray(out[k]),
            ef[k].reshape((2, 2) + ef[k].shape[1:]).sum(axis=1),
            rtol=1e-6,
        )
        # ...so the telescoping total is preserved exactly
        np.testing.assert_allclose(
            np.asarray(out[k]).sum(axis=0), ef[k].sum(axis=0), rtol=1e-6
        )
    with pytest.raises(ValueError, match="divide"):
        retile_error_feedback(ef, 3)


def test_retile_error_feedback_sharded_at_birth(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_llms_example_tpu.ops.quant_collectives import (
        retile_error_feedback,
    )

    ef = {"w": np.arange(4 * 8 * 4, dtype=np.float32).reshape(4, 8, 4)}
    sh = {"w": NamedSharding(mesh8, P("data", "fsdp", None))}
    out = retile_error_feedback(ef, 2, sh)
    assert out["w"].sharding == sh["w"]
    np.testing.assert_allclose(
        np.asarray(jax.device_get(out["w"])),
        ef["w"].reshape(2, 2, 8, 4).sum(axis=1),
        rtol=1e-6,
    )


# ---------------------------------------------------------------------------
# the spec-lint reshard proof pass
# ---------------------------------------------------------------------------

def _abstract_params():
    return {
        "decoder": {
            "self_attn": {"q_proj": {"kernel": jax.ShapeDtypeStruct((64, 64), np.float32)}},
            "mlp": {"wi": {"kernel": jax.ShapeDtypeStruct((64, 128), np.float32)}},
        }
    }


def test_ef_restore_target_same_workers_keeps_ef(mesh8):
    """Regression: a SAME-topology --grad-compression int8 resume must
    hand orbax a target that still CARRIES the error-feedback tree (the
    payload has one) — the ef-less abstract template would fail every
    candidate step's restore on structure mismatch."""
    import dataclasses

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.train.trainer import Trainer

    @dataclasses.dataclass
    class FakeState:
        params: object
        ef: object

        def replace(self, **kw):
            return dataclasses.replace(self, **kw)

    params = {"w": jax.ShapeDtypeStruct((8, 16), np.float32)}
    fake = type("FakeTrainer", (), {})()
    fake.state = FakeState(params=params, ef={"w": object()})  # live EF on
    fake._grad_workers = 2
    fake.mesh = mesh8
    fake.state_sh = FakeState(
        params={"w": NamedSharding(mesh8, P("fsdp", None))}, ef=None
    )
    abstract = FakeState(params=params, ef=None)  # template is ef-less
    target, mode = Trainer._ef_restore_target(fake, abstract, saved_workers=2)
    assert mode == ""
    assert target.ef is not None
    (leaf,) = jax.tree.leaves(target.ef)
    assert tuple(leaf.shape) == (2, 8, 16)


def test_reshard_lint_green_on_data_fsdp_refactorization():
    from distributed_llms_example_tpu.analysis.spec_lint import lint_reshard_layout

    saved = {"axes": {"data": 2, "fsdp": 4}, "processes": 2, "ef_workers": 0}
    findings = lint_reshard_layout(saved, {"data": 4, "fsdp": 2}, _abstract_params())
    assert [f for f in findings if f.severity == "error"] == []


def test_reshard_lint_errors_on_unmappable_factorizations():
    from distributed_llms_example_tpu.analysis.spec_lint import lint_reshard_layout

    params = _abstract_params()
    # expert>1 → expert=1: the satellite fix — a NAMED error instead of
    # an opaque restore exception deep in the walk-back
    saved = {"axes": {"data": 2, "expert": 2}, "processes": 2, "ef_workers": 0}
    codes = [f.code for f in lint_reshard_layout(saved, {"data": 8}, params)
             if f.severity == "error"]
    assert "reshard-expert-mismatch" in codes
    # stage moves are the composition row's territory
    saved = {"axes": {"stage": 2, "data": 4}, "processes": 1, "ef_workers": 0}
    codes = [f.code for f in lint_reshard_layout(saved, {"data": 8}, params)
             if f.severity == "error"]
    assert "reshard-stage-mismatch" in codes
    # an axis name this build does not know
    saved = {"axes": {"hyper": 4}, "processes": 1, "ef_workers": 0}
    codes = [f.code for f in lint_reshard_layout(saved, {"data": 8}, params)
             if f.severity == "error"]
    assert "unknown-saved-axis" in codes


def test_reshard_lint_ef_transition_findings():
    from distributed_llms_example_tpu.analysis.spec_lint import lint_reshard_layout

    params = _abstract_params()
    saved = {"axes": {"data": 8}, "processes": 2, "ef_workers": 8}
    # 8 → 4 workers divides: re-tile, info
    f = [x for x in lint_reshard_layout(saved, {"data": 4, "fsdp": 2}, params)
         if x.code == "reshard-ef-retile"]
    assert len(f) == 1 and f[0].severity == "info"
    # 8 → 3 does not: zero-fill, warning
    f = [x for x in lint_reshard_layout(saved, {"data": 3}, params)
         if x.code == "reshard-ef-zero-fill"]
    assert len(f) == 1 and f[0].severity == "warning"


def test_reshard_lint_cli_wiring():
    from distributed_llms_example_tpu.analysis.lint import main as lint_main

    rc = lint_main([
        "--model", "t5-test", "--mesh", "data=4,fsdp=2",
        "--reshard-from", "data=2,fsdp=4", "--reshard-processes", "2",
        "--no-ir",
    ])
    assert rc == 0
    rc = lint_main([
        "--model", "t5-test", "--mesh", "data=8",
        "--reshard-from", "data=2,fsdp=2,expert=2", "--no-ir",
    ])
    assert rc == 1  # expert move = error
    # stage UNCHANGED across a data/fsdp refactorization is the normal
    # pipelined resume: the reshard-pipelined composition row stays
    # silent (only a stage MOVE is its territory — matching the
    # trainer's _check_reshardable judgement)
    rc = lint_main([
        "--model", "llama-test", "--mesh", "stage=2,data=4",
        "--reshard-from", "stage=2,data=2,fsdp=2", "--no-ir",
    ])
    assert rc == 0
    rc = lint_main([
        "--model", "llama-test", "--mesh", "stage=2,data=4",
        "--reshard-from", "data=8", "--no-ir",
    ])
    assert rc == 1  # stage MOVED (1 → 2): composition row + spec error
    # the saved topology is a historical fact: an unpinned axis would
    # resolve against THIS host's device count and lint a factorization
    # that was never saved — rejected, not guessed
    rc = lint_main([
        "--model", "t5-test", "--mesh", "data=8",
        "--reshard-from", "fsdp=4", "--no-ir",
    ])
    assert rc == 1  # data unspecified (-1) in --reshard-from


def test_reshard_pipelined_composition_row():
    from distributed_llms_example_tpu.analysis.composition import (
        failing_combos,
        reason_for,
    )

    assert "stage" in reason_for("reshard-pipelined")
    rows = failing_combos(
        family="llama", schedule="gpipe", mesh_axes={"stage": 2, "data": 4},
        flags=("reshard", "pipelined"),
    )
    assert any(r.id == "reshard-pipelined" for r in rows)
    # without the reshard flag the row stays silent (normal pipelining)
    rows = failing_combos(
        family="llama", schedule="gpipe", mesh_axes={"stage": 2, "data": 4},
        flags=("pipelined",),
    )
    assert not any(r.id == "reshard-pipelined" for r in rows)


def test_rebuild_for_mesh_recomputes_startup_gauges(tmp_path):
    """The PR 14 caveat, fixed and pinned: an in-process reshard rebuilds
    the train step against the NEW mesh, so the startup obs gauges (MFU
    FLOPs numerator, collective-traffic account, devprof's
    instruction→bucket index) must be recomputed from the rebuilt step —
    `_rebuild_for_mesh` re-invokes `startup_gauges` with the new mesh
    instead of leaving the old mesh's numbers live until restart."""
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.obs import TrainerObs
    from distributed_llms_example_tpu.train.trainer import Trainer

    calls: list[dict] = []

    real = TrainerObs.startup_gauges

    def recording(self, mesh, *, tgt_cap):
        calls.append({"mesh": dict(mesh.shape), "tgt_cap": tgt_cap})

    TrainerObs.startup_gauges = recording
    try:
        t = Trainer(
            _run_cfg(tmp_path / "run", MeshConfig(data=2, fsdp=4),
                     resume=False),
            train_records=_records(),
        )
        assert len(calls) == 1  # the normal startup compile
        t._rebuild_for_mesh(build_mesh(MeshConfig(data=8, fsdp=1)))
    finally:
        TrainerObs.startup_gauges = real
    assert len(calls) == 2
    assert calls[1]["mesh"]["data"] == 8 and calls[1]["mesh"]["fsdp"] == 1
    assert calls[1]["tgt_cap"] == calls[0]["tgt_cap"]


# ---------------------------------------------------------------------------
# chaos grammar + config validation + batching revalidation
# ---------------------------------------------------------------------------

def test_chaos_grammar_host_loss():
    s = parse_chaos("host_loss@7,nan_grad@3")
    assert s.armed_at("host_loss") == [7]
    with pytest.raises(ValueError, match="host_loss"):
        parse_chaos("host_loss@")


def test_config_host_loss_requires_checkpointing():
    import argparse

    from distributed_llms_example_tpu.core.config import (
        add_tpu_args,
        config_from_args,
    )

    def cfg_from(*argv):
        p = argparse.ArgumentParser()
        add_tpu_args(p)
        return config_from_args(p.parse_args(list(argv)))

    with pytest.raises(ValueError, match="reshard FROM"):
        cfg_from("--chaos", "host_loss@3")
    cfg = cfg_from("--chaos", "host_loss@3", "--save-every-steps", "2")
    assert cfg.on_host_loss == "reshard"
    cfg = cfg_from("--chaos", "host_loss@3", "--on-host-loss", "halt")
    assert cfg.on_host_loss == "halt"  # halt needs no checkpoint cadence


def test_validate_batch_mesh():
    from distributed_llms_example_tpu.data.batching import validate_batch_mesh

    validate_batch_mesh(8, {"data": 4, "fsdp": 2})
    validate_batch_mesh(8, {"data": 2, "fsdp": 2}, process_count=2,
                        grad_accum_steps=2)
    with pytest.raises(ValueError, match="batch shards"):
        validate_batch_mesh(8, {"data": 4, "fsdp": 4})
    with pytest.raises(ValueError, match="processes"):
        validate_batch_mesh(9, {"data": 1}, process_count=2)


# ---------------------------------------------------------------------------
# obs.report topology timeline
# ---------------------------------------------------------------------------

def _write_jsonl(outdir, events):
    obs_dir = os.path.join(outdir, "obs")
    os.makedirs(obs_dir, exist_ok=True)
    with open(os.path.join(obs_dir, "metrics-p000.jsonl"), "w") as f:
        for e in events:
            f.write(json.dumps({"schema_version": 1, **e}) + "\n")


_TOPO_EVENTS = [
    {"event": "topology_change", "step": 3,
     "old_mesh": {"data": 2, "fsdp": 4}, "old_processes": 2,
     "policy": "reshard"},
    {"event": "reshard_restore", "step": 2, "detected_at_step": 3,
     "old_mesh": {"data": 2, "fsdp": 4}, "old_processes": 2,
     "new_mesh": {"data": 1, "fsdp": 4}, "new_processes": 1,
     "ef_mode": "none", "steps_lost": 1, "reshard_wall_s": 0.75},
]


def test_report_topology_timeline_injected(tmp_path):
    from distributed_llms_example_tpu.obs import report as report_mod

    _write_jsonl(str(tmp_path), [
        {"event": "chaos_injection", "kind": "host_loss", "step": 3},
        *_TOPO_EVENTS,
    ])
    report = build_report(str(tmp_path))
    rec = report["recovery"]
    assert rec["topology"] == [{
        "step": 3, "policy": "reshard",
        "old_mesh": {"data": 2, "fsdp": 4}, "old_processes": 2,
    }]
    assert len(rec["reshards"]) == 1
    assert rec["reshards"][0]["new_processes"] == 1
    # reshard wall-clock counts toward MTTR; its lost steps toward the total
    assert rec["mttr_s"] == 0.75
    assert rec["steps_lost_total"] == 1
    # the injected split: the host_loss firing explains the fault
    assert [f["kind"] for f in rec["faults"]] == ["topology_change"]
    assert rec["faults"][0]["injected"] is True
    assert rec["organic_faults"] == []
    md = render_markdown(report)
    assert "topology change" in md and "reshard restore" in md
    assert report_mod.main([str(tmp_path), "--strict"]) == 0


def test_report_topology_organic_fails_strict(tmp_path):
    from distributed_llms_example_tpu.obs import report as report_mod

    _write_jsonl(str(tmp_path), _TOPO_EVENTS)  # no chaos_injection
    report = build_report(str(tmp_path))
    rec = report["recovery"]
    assert [f["kind"] for f in rec["organic_faults"]] == ["topology_change"]
    assert report_mod.main([str(tmp_path), "--strict"]) == 1


# ---------------------------------------------------------------------------
# e2e: resharding restore + topology change (slow: trainer compiles)
# ---------------------------------------------------------------------------

def _records(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(12)),
            "summary": f"w{rng.randint(40)}",
        }
        for _ in range(n)
    ]


def _run_cfg(out, mesh, *, resume, epochs=1, **over) -> TrainConfig:
    kw = dict(
        model_ckpt="t5-test",
        output_dir=str(out),
        batch_size=8,
        num_epochs=epochs,
        warmup_steps=1,
        evaluation_steps=0,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=2,
        num_beams=1,
        tokenizer="byte",
        mesh=mesh,
        checkpoint=CheckpointConfig(save_every_steps=2, resume=resume, async_save=False),
        obs="jsonl",
        obs_gauges="off",
        health="on",
        recorder_steps=8,
    )
    kw.update(over)
    return TrainConfig(**kw)


def _leaves(params):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(params))]


def _events(outdir):
    path = os.path.join(str(outdir), "obs", "metrics-p000.jsonl")
    return [json.loads(line) for line in open(path)]


@pytest.mark.slow
def test_reshard_restore_across_factorizations(tmp_path):
    """Save under data=2×fsdp=4; resume under 4×2, then 8×1 — params
    BIT-EQUAL after every reshard, ``reshard_restore`` stamped with the
    old→new factorizations, and a SAME-mesh resume stays on the
    non-reshard path (no event: zero regressions on PR 6's contract)."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "run"
    t1 = Trainer(_run_cfg(out, MeshConfig(data=2, fsdp=4), resume=False),
                 train_records=recs)
    t1.save_final = lambda: None
    assert t1.train()["steps"] == 2
    p1 = _leaves(t1.state.params)

    # same-mesh resume first: bit-identical to the pre-reshard behavior,
    # and NO reshard event
    t_same = Trainer(_run_cfg(out, MeshConfig(data=2, fsdp=4), resume=True),
                     train_records=recs)
    assert t_same.start_step == 2
    for a, b in zip(p1, _leaves(t_same.state.params)):
        np.testing.assert_array_equal(a, b)
    assert not [e for e in _events(out) if e.get("event") == "reshard_restore"]

    # 2×4 → 4×2
    t2 = Trainer(_run_cfg(out, MeshConfig(data=4, fsdp=2), resume=True),
                 train_records=recs)
    assert t2.start_step == 2
    for a, b in zip(p1, _leaves(t2.state.params)):
        np.testing.assert_array_equal(a, b)
    rr = [e for e in _events(out) if e.get("event") == "reshard_restore"]
    assert len(rr) == 1
    assert rr[0]["old_mesh"]["data"] == 2 and rr[0]["old_mesh"]["fsdp"] == 4
    assert rr[0]["new_mesh"]["data"] == 4 and rr[0]["new_mesh"]["fsdp"] == 2

    # 2×4 → 8×1, and TRAIN through the resharded state (epoch 2 runs)
    t3 = Trainer(_run_cfg(out, MeshConfig(data=8, fsdp=1), resume=True, epochs=2),
                 train_records=recs)
    t3.save_final = lambda: None
    assert t3.start_step == 2
    r3 = t3.train()
    assert r3["steps"] == 4
    losses = [e["loss"] for e in _events(out) if "loss" in e and "step" in e]
    assert losses and np.isfinite(losses[-1])


@pytest.mark.slow
def test_restore_target_candidates_without_orbax_metadata(tmp_path):
    """A step whose orbax metadata is unreadable cannot be classified —
    the target builder must offer the full candidate-structure ladder
    (modern mesh-leaf payload first, the pre-mesh-leaf and flag-flip
    shapes, legacy bare state last) instead of one guessed structure,
    and ``_finish_restore`` must classify by what actually landed.
    (A restore e2e is unconstructible here: this orbax version stores
    ALL structure in ``_METADATA``, so a dir without one cannot restore
    under ANY target — the ladder exists for ancient aggregate-format
    dirs, whose writer we no longer have.)"""
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "run"
    t1 = Trainer(_run_cfg(out, MeshConfig(data=2, fsdp=4), resume=False),
                 train_records=recs)
    t1.save_final = lambda: None
    assert t1.train()["steps"] == 2

    t1.checkpointer.payload_metadata = lambda step: None
    t1._reshard_plan = {}
    cands = t1._restore_target_for(2)
    assert isinstance(cands, list) and len(cands) == 6
    # modern mesh-leaf payload first, then the pre-mesh-leaf shape
    assert isinstance(cands[0], dict) and "mesh_layout" in cands[0]
    assert isinstance(cands[1], dict) and "mesh_layout" not in cands[1]
    # legacy bare states last
    assert not isinstance(cands[4], dict) and not isinstance(cands[5], dict)
    plan = t1._reshard_plan[2]
    assert plan["structure_unknown"] and not plan["legacy"]
    # a bare TrainState landing is classified as legacy, EF transition
    # resolved from the restored tree (off run, no EF: mode stays "")
    state, plan = t1._finish_restore(t1.state, 2)
    assert plan["legacy"] and state is t1.state


@pytest.mark.slow
def test_reshard_ef_retile_and_zero_fill_directions(tmp_path):
    """`--grad-compression int8` across a topology change: the EF worker
    dim follows the replica axes, so the reshard must re-handle it —
    4→2 workers RE-TILES (merged groups' residuals sum; the telescoping
    total is preserved, pinned against the saved tree), 4→8 ZERO-FILLS
    (no regrouping preserves per-worker error), both stamped as
    ``grad_compression_ef_reshaped``."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "run"
    cfg = _run_cfg(out, MeshConfig(data=4, fsdp=2), resume=False,
                   grad_compression="int8")
    t1 = Trainer(cfg, train_records=recs)
    t1.save_final = lambda: None
    t1.train()
    ef_saved = {  # (4, *shape) leaves as saved
        path: np.asarray(x)
        for path, x in zip(
            ("l%d" % i for i in range(10**6)),
            jax.tree.leaves(jax.device_get(t1.state.ef)),
        )
    }

    # same mesh, same workers first (regression: the restore target must
    # CARRY the EF tree — an ef-less target failed every same-topology
    # int8 resume on structure mismatch): EF restores bit-equal, no
    # reshape event
    t_same = Trainer(
        _run_cfg(out, MeshConfig(data=4, fsdp=2), resume=True,
                 grad_compression="int8"),
        train_records=recs,
    )
    assert t_same.start_step == 2
    for saved, got in zip(
        ef_saved.values(), jax.tree.leaves(jax.device_get(t_same.state.ef))
    ):
        np.testing.assert_array_equal(np.asarray(got), saved)
    assert not [e for e in _events(out)
                if e.get("event") == "grad_compression_ef_reshaped"]

    # 4 → 2 workers: re-tile (2 divides 4)
    t2 = Trainer(
        _run_cfg(out, MeshConfig(data=2, fsdp=4), resume=True,
                 grad_compression="int8"),
        train_records=recs,
    )
    assert t2.start_step == 2
    ev = _events(out)
    reshaped = [e for e in ev if e.get("event") == "grad_compression_ef_reshaped"]
    assert len(reshaped) == 1 and reshaped[0]["mode"] == "retile"
    assert (reshaped[0]["from_workers"], reshaped[0]["to_workers"]) == (4, 2)
    for saved, got in zip(
        ef_saved.values(), jax.tree.leaves(jax.device_get(t2.state.ef))
    ):
        got = np.asarray(got)
        assert got.shape[0] == 2
        # merged groups sum; the telescoping total is preserved (atol:
        # residual totals near-cancel, where reassociation noise makes a
        # relative bound meaningless)
        np.testing.assert_allclose(
            got, saved.reshape((2, 2) + saved.shape[1:]).sum(axis=1), rtol=1e-6
        )
        np.testing.assert_allclose(
            got.sum(axis=0), saved.sum(axis=0), rtol=1e-5, atol=1e-7
        )

    # 4 → 8 workers: zero-fill (4 % 8 != 0 — no regrouping exists)
    t3 = Trainer(
        _run_cfg(out, MeshConfig(data=8, fsdp=1), resume=True,
                 grad_compression="int8"),
        train_records=recs,
    )
    assert t3.start_step == 2
    ev = _events(out)
    zf = [e for e in ev if e.get("event") == "grad_compression_ef_reshaped"
          and e.get("mode") == "zero_fill"]
    assert len(zf) == 1 and (zf[0]["from_workers"], zf[0]["to_workers"]) == (4, 8)
    for got in jax.tree.leaves(jax.device_get(t3.state.ef)):
        assert np.asarray(got).shape[0] == 8
        assert not np.asarray(got).any()

    # ...and the flag-flip direction still works ACROSS the reshard:
    # int8 checkpoint resumed by an OFF run on a different factorization
    t4 = Trainer(
        _run_cfg(out, MeshConfig(data=8, fsdp=1), resume=True),
        train_records=recs,
    )
    assert t4.start_step == 2 and t4.state.ef is None
    dropped = [e for e in _events(out)
               if e.get("event") == "grad_compression_ef_dropped"]
    assert dropped


@pytest.mark.slow
def test_reshard_failfast_on_expert_mismatch(tmp_path):
    """The satellite fix: a checkpoint whose recorded topology names an
    expert factorization the live mesh cannot map fails FAST with both
    factorizations in the message — not as an opaque orbax structure
    error deep in the walk-back."""
    from distributed_llms_example_tpu.io.checkpoint import ReshardError
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "run"
    t1 = Trainer(_run_cfg(out, MeshConfig(data=2, fsdp=4), resume=False),
                 train_records=recs)
    t1.save_final = lambda: None
    t1.train()
    # doctor the recovery sidecar to claim an expert-parallel topology
    side_path = os.path.join(str(out), "checkpoints", "recovery-2.json")
    side = json.load(open(side_path))
    side["mesh_layout"]["axes"]["expert"] = 2
    side["mesh_layout"]["axes"]["data"] = 1
    json.dump(side, open(side_path, "w"))
    with pytest.raises(ReshardError, match="expert") as exc:
        Trainer(_run_cfg(out, MeshConfig(data=8, fsdp=1), resume=True),
                train_records=recs)
    # both factorizations are named in the message
    assert "expert=2" in str(exc.value)
    assert "data=8" in str(exc.value)


@pytest.mark.slow
def test_host_loss_topology_change_e2e(tmp_path):
    """``--chaos host_loss@3`` with the in-process reshard policy: the
    trainer tears down, rebuilds onto the override mesh (4×2), restores
    the step-2 checkpoint through the resharding path, resumes from the
    sidecar cursor, and FINISHES — with the topology timeline strict-
    green (the one fault is injected) and reshard wall in MTTR."""
    from distributed_llms_example_tpu.obs import report as report_mod
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "chaos"
    cfg = _run_cfg(out, MeshConfig(data=2, fsdp=4), resume=False, epochs=3,
                   chaos="host_loss@3")
    t = Trainer(cfg, train_records=recs)
    t.save_final = lambda: None
    t._next_mesh_override = MeshSpec(data=4, fsdp=2, sequence=1, tensor=1)
    result = t.train()
    assert "anomaly" not in result
    assert result["steps"] == 6  # 3 epochs × 2 steps, one step replayed
    assert dict(t.mesh.shape)["data"] == 4  # training ENDED on the new mesh

    ev = _events(out)
    by = {}
    for e in ev:
        by.setdefault(e.get("event"), []).append(e)
    assert [(e["kind"], e["step"]) for e in by["chaos_injection"]] == [
        ("host_loss", 3)
    ]
    tc = by["topology_change"]
    assert len(tc) == 1 and tc[0]["policy"] == "reshard"
    assert tc[0]["old_mesh"]["data"] == 2
    rr = by["reshard_restore"]
    assert len(rr) == 1
    assert rr[0]["step"] == 2 and rr[0]["detected_at_step"] == 3
    assert rr[0]["new_mesh"]["data"] == 4 and rr[0]["steps_lost"] == 1
    assert rr[0]["reshard_wall_s"] > 0
    losses = [e["loss"] for e in ev if "loss" in e and "step" in e]
    assert losses and np.isfinite(losses[-1])

    report = build_report(str(out))
    rec = report["recovery"]
    assert len(rec["topology"]) == 1 and len(rec["reshards"]) == 1
    assert rec["mttr_s"] is not None and rec["mttr_s"] > 0
    assert rec["organic_faults"] == []
    assert report_mod.main([str(out), "--strict"]) == 0


# ---------------------------------------------------------------------------
# THE ROADMAP ACCEPTANCE RUN: 2 processes killed down to 1 (slow)
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = "distributed_llms_example_tpu.launch.cli"

# the gloo/coordination-service failure modes this container produces on
# an otherwise-green run (identical list and rationale as
# tests/test_multiprocess.py — the rendezvous itself is ~every-other-run
# flaky here, verified pre-existing): ONLY these retry
_INFRA_FLAKE_SIGNATURES = (
    "op.preamble",
    "Connection closed by peer",
    "heartbeat timeout",
    "coordination service",
    "CoordinationService",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(n_local_devices: int, *, rank: int | None = None,
               world: int | None = None, port: int | None = None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_local_devices}"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK",
              "MASTER_ADDR", "WORLD_SIZE", "RANK"):
        env.pop(k, None)
    if rank is not None:
        env["VH_MASTER_IP"] = f"127.0.0.1:{port}"
        env["VH_WORLD_SIZE"] = str(world)
        env["VH_RANK"] = str(rank)
    return env


def _cli_args(outdir: str, train: str, **over) -> list[str]:
    opts = {
        "model-ckpt": "t5-test",
        "output-dir": outdir,
        "batch-size": 8,
        "num-epochs": 2,
        "train-file": train,
        # data absorbs the process count: 2 procs × 4 devices → data=2,
        # 1 proc × 4 devices → data=1 — the reshard under test
        "mesh": "data=-1,fsdp=4",
        "compute-dtype": "float32",
        "log-every-steps": 1,
        "save-every-steps": 2,
        "evaluation-steps": 0,
        "tokenizer": "byte",
        "max-source-length": 32,
        "max-target-length": 16,
        "pad-to-multiple": 32,
        "num-beams": 1,
    }
    opts.update(over)
    args = [sys.executable, "-m", CLI]
    for k, v in opts.items():
        args += [f"--{k}", str(v)]
    return args


def _stdout_events(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def _final_safetensors(outdir: str) -> dict:
    from safetensors.numpy import load_file

    return load_file(os.path.join(outdir, "model", "model.safetensors"))


@pytest.mark.slow
def test_two_process_killed_to_one_process_resharding_resume(tmp_path):
    """The ROADMAP acceptance run: a 2-process CPU run is killed; a
    1-process run over the same output dir resumes THROUGH the
    resharding restore (data=2×2procs → data=1×1proc) and matches the
    clean 1-process run from the same checkpoint — identical loss
    trajectory, bit-equal final params.  Bounded targeted retry for the
    container's known gloo rendezvous flake, exactly like
    tests/test_multiprocess.py."""
    last: Exception | None = None
    for attempt in range(4):
        root = tmp_path / f"attempt{attempt}"
        root.mkdir()
        try:
            _two_to_one_cycle(root)
            return
        except (Exception, pytest.fail.Exception) as e:
            text = str(e)
            if not any(sig in text for sig in _INFRA_FLAKE_SIGNATURES):
                raise
            last = e
    assert last is not None
    raise last


def _two_to_one_cycle(tmp_path):
    recs = _records(40)
    train = str(tmp_path / "train.json")
    with open(train, "w") as f:
        json.dump(recs, f)
    outdir = str(tmp_path / "out")
    port = _free_port()
    errs = [open(str(tmp_path / f"err{r}.log"), "w") for r in range(2)]

    # ---- leg A: the 2-process run (data=2, fsdp=4 over 2×4 devices),
    # killed via SIGTERM on rank 0 after a few steps — the preemption
    # path checkpoints at the agreed step with the recovery sidecar
    procs = [
        subprocess.Popen(
            _cli_args(outdir, train, **{"num-epochs": 40}),
            env=_child_env(4, rank=r, world=2, port=port),
            cwd=REPO, stdout=subprocess.PIPE, stderr=errs[r], text=True,
        )
        for r in range(2)
    ]
    buf = []
    deadline = time.time() + 420
    while time.time() < deadline:
        line = procs[0].stdout.readline()
        if not line:
            break
        buf.append(line)
        if '"step": 3' in line:
            procs[0].send_signal(signal.SIGTERM)
            break
    else:
        pytest.fail("rank 0 never reached step 3")
    rest0, _ = procs[0].communicate(timeout=420)
    procs[1].communicate(timeout=420)
    for f in errs:
        f.close()
    for r, p in enumerate(procs):
        assert p.returncode == 0, open(str(tmp_path / f"err{r}.log")).read()[-3000:]
    ev0 = _stdout_events("".join(buf) + rest0)
    pre = [e for e in ev0 if e.get("event") == "preempted"]
    assert pre, "rank 0 did not checkpoint-and-exit on SIGTERM"
    stopped_at = pre[0]["step"]
    ckpt_dir = os.path.join(outdir, "checkpoints")
    assert os.path.isdir(os.path.join(ckpt_dir, str(stopped_at)))
    # the recovery sidecar recorded the 2-process topology
    side = json.load(open(os.path.join(ckpt_dir, f"recovery-{stopped_at}.json")))
    assert side["mesh_layout"]["processes"] == 2
    assert side["mesh_layout"]["axes"]["data"] == 2

    # the CLEAN copy: the same checkpoint, untouched by the kill's dir
    clean_out = outdir + "-clean"
    shutil.copytree(outdir, clean_out)

    # ---- leg B: killed dir resumed by ONE process on 4 devices —
    # through the resharding restore (data=2×2p → data=1×1p)
    def one_proc_resume(d: str) -> tuple[list[dict], dict]:
        r = subprocess.run(
            _cli_args(d, train, **{"num-epochs": 2}),
            env=_child_env(4), cwd=REPO,
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stderr[-3000:]
        return _stdout_events(r.stdout), _final_safetensors(d)

    ev_b, params_b = one_proc_resume(outdir)
    # ---- leg C: the clean 1-process run from the SAME checkpoint
    ev_c, params_c = one_proc_resume(clean_out)

    for ev in (ev_b, ev_c):
        assert any(
            e.get("event") == "resumed" and e["step"] == stopped_at for e in ev
        )
        rr = [e for e in ev if e.get("event") == "reshard_restore"]
        assert len(rr) == 1
        assert rr[0]["old_processes"] == 2 and rr[0]["new_processes"] == 1
        assert rr[0]["old_mesh"]["data"] == 2 and rr[0]["new_mesh"]["data"] == 1
        assert any(e.get("event") == "done" for e in ev)

    # identical loss trajectory, step for step...
    losses_b = {e["step"]: e["loss"] for e in ev_b if "loss" in e and "step" in e}
    losses_c = {e["step"]: e["loss"] for e in ev_c if "loss" in e and "step" in e}
    assert losses_b and losses_b == losses_c
    assert min(losses_b) > stopped_at  # the resumes CONTINUED, not restarted
    # ...and bit-equal final params: the resharding path introduced no
    # numeric drift over the clean run from the same checkpoint
    assert set(params_b) == set(params_c)
    for k in params_b:
        np.testing.assert_array_equal(params_b[k], params_c[k])


@pytest.mark.slow
def test_host_loss_halt_policy(tmp_path):
    """``--on-host-loss halt``: the evidence-preserving stop — a
    resumable checkpoint lands, the run ends with the anomaly marker,
    and a later resume (on any factorization) reshards its way back."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "halt"
    cfg = _run_cfg(out, MeshConfig(data=2, fsdp=4), resume=False, epochs=3,
                   chaos="host_loss@3", on_host_loss="halt")
    t = Trainer(cfg, train_records=recs)
    t.save_final = lambda: None
    result = t.train()
    assert result.get("anomaly") == "checkpoint"
    ev = _events(out)
    tc = [e for e in ev if e.get("event") == "topology_change"]
    assert len(tc) == 1 and tc[0]["policy"] == "halt"
    # the halted run's checkpoint resumes on a re-factorized mesh
    t2 = Trainer(_run_cfg(out, MeshConfig(data=8, fsdp=1), resume=True),
                 train_records=recs)
    assert t2.start_step == 3
