"""Decode capacity: int8 KV cache, paged block-pool allocation, bucketed
prefill (ISSUE 13) — allocator properties, kernel parity, engine token
parity (paged+bucketed bit-identical to flat; int8 at a stated tolerance),
zero-recompile churn, capacity gauges, pool spec lint."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.ops.attention import NEG_INF, dot_product_attention
from distributed_llms_example_tpu.ops.flash_attention import (
    dequantize_kv,
    flash_decode,
    flash_decode_paged,
    quantize_kv,
)
from distributed_llms_example_tpu.serving import cache_pool
from distributed_llms_example_tpu.serving.engine import (
    ServeConfig,
    ServingEngine,
    static_batch_generate,
    trim_eos,
)


# ------------------------------------------------------------- allocator


def test_pool_alloc_free_properties():
    """Property sweep: random interleaved alloc/free keeps the invariants —
    no block handed out twice, free+used == total, and (blocks being
    identityless) any request within the free count succeeds no matter how
    fragmented the history (fragmentation cannot strand capacity)."""
    rng = np.random.RandomState(0)
    pool = cache_pool.CachePool(num_blocks=37, block_size=8)
    held: list[list[int]] = []
    seen_concurrent: set[int] = set()
    for _ in range(500):
        if held and rng.rand() < 0.45:
            grant = held.pop(rng.randint(len(held)))
            pool.free(grant)
            seen_concurrent.difference_update(grant)
        else:
            n = int(rng.randint(1, 9))
            grant = pool.alloc(n)
            if n <= 37 - len(seen_concurrent):
                assert grant is not None and len(grant) == n
            if grant is None:
                continue
            assert not (set(grant) & seen_concurrent), "block double-granted"
            seen_concurrent.update(grant)
            held.append(grant)
        assert pool.blocks_free + pool.blocks_in_use == 37
        assert pool.blocks_in_use == len(seen_concurrent)
    for grant in held:
        pool.free(grant)
    assert pool.blocks_in_use == 0 and pool.blocks_free == 37
    # after arbitrary churn, a full-pool request still succeeds whole
    assert pool.alloc(37) is not None


def test_pool_refusal_and_free_errors():
    pool = cache_pool.CachePool(num_blocks=4, block_size=8)
    got = pool.alloc(3)
    assert got is not None
    # refusal is total, never a partial grant
    assert pool.alloc(2) is None
    assert pool.blocks_free == 1
    pool.free(got)
    with pytest.raises(ValueError, match="double-free|not allocated"):
        pool.free(got)
    with pytest.raises(ValueError, match="not allocated"):
        pool.free([99])


def test_blocks_needed_and_block_row():
    # 5-token prompt at block 8 → 1 block; 9 → 2; budget 8 → 1
    assert cache_pool.blocks_needed(5, 8, 8) == 2
    assert cache_pool.blocks_needed(9, 8, 8) == 3
    row = cache_pool.build_block_row(
        6, [10, 11, 12], prompt_len=9, bucket_width=32, budget=8,
        block_size=8, sentinel=99,
    )
    # prompt tiles [0,2) allocated, gap [2,4) sentinel, decode tile at
    # 32//8 = 4 allocated, tail sentinel
    assert row.tolist() == [10, 11, 99, 99, 12, 99]
    with pytest.raises(ValueError, match="multiple of the block size"):
        cache_pool.build_block_row(
            6, [1, 2], prompt_len=3, bucket_width=20, budget=4,
            block_size=8, sentinel=99,
        )


def test_gather_scatter_round_trip():
    """Pool plumbing unit: admit-scatter then gather reconstructs the
    chunk view exactly (zeros at sentinel tiles); step-scatter lands one
    row in the owning block; sentinel/parked writes drop."""
    S, H, bs, D, nt = 2, 2, 4, 3, 3
    N = 5
    rng = np.random.RandomState(1)
    chunk = jnp.asarray(rng.randn(S, H, nt * bs, D).astype(np.float32))
    pool_tree = {"cached_key": jnp.zeros((N, H, bs, D), jnp.float32)}
    # row 0: tiles 0,1 → blocks 0,1; row 1: tile 0 → block 2; rest sentinel
    admit = jnp.asarray(np.array([0, 1, N, 2, N, N], np.int32))
    pool_tree = cache_pool.scatter_admit(
        pool_tree, {"cached_key": chunk}, admit, bs
    )
    bt = jnp.asarray(np.array([[0, 1, N], [2, N, N]], np.int32))
    view = cache_pool.gather_cache(pool_tree, bt)["cached_key"]
    want = np.asarray(chunk).copy()
    want[0, :, 2 * bs :, :] = 0.0
    want[1, :, bs:, :] = 0.0
    np.testing.assert_array_equal(np.asarray(view), want)
    # step write at position 5 of row 0 (tile 1, in-block 1) and a PARKED
    # row 1 (offset = width → must drop)
    new_cache = {"cached_key": jnp.asarray(rng.randn(S, H, nt * bs, D).astype(np.float32))}
    offs = jnp.asarray(np.array([5, nt * bs], np.int32))
    before = np.asarray(pool_tree["cached_key"]).copy()
    pool_tree = cache_pool.scatter_step(
        pool_tree, new_cache, bt, offs, num_blocks=N, block_size=bs
    )
    after = np.asarray(pool_tree["cached_key"])
    # row 0's position 5 = tile 1, in-block slot 1 → exactly block 1
    # changed, at exactly that slot
    np.testing.assert_array_equal(
        after[1, :, 1, :], np.asarray(new_cache["cached_key"])[0, :, 5, :]
    )
    untouched = np.ones((bs,), bool)
    untouched[1] = False
    np.testing.assert_array_equal(
        after[1][:, untouched, :], before[1][:, untouched, :]
    )
    # every other block untouched — including row 1's (PARKED: offset =
    # width → the write dropped) and the never-allocated spares
    for blk in (0, 2, 3, 4):
        np.testing.assert_array_equal(after[blk], before[blk])


def test_tree_bytes_and_block_bytes():
    tree = {
        "k": jax.ShapeDtypeStruct((4, 2, 8, 4), jnp.int8),
        "s": jax.ShapeDtypeStruct((4, 2, 8), jnp.float32),
        "i": jax.ShapeDtypeStruct((), jnp.int32),
    }
    assert cache_pool.tree_bytes(tree) == 4 * 2 * 8 * 4 + 4 * 2 * 8 * 4 + 4
    assert cache_pool.block_bytes(tree, 4) == 2 * 8 * 4 + 2 * 8 * 4


# ----------------------------------------------------- int8 quantization


def test_quantize_kv_round_trip_bound():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 3, 5, 16).astype(np.float32) * 3.0)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 3, 5)
    err = np.abs(np.asarray(dequantize_kv(q, s)) - np.asarray(x))
    # symmetric round-to-nearest: |err| <= scale/2 per element
    assert (err <= np.asarray(s)[..., None] / 2 + 1e-7).all()
    # all-zero rows stay exactly zero (scale guard, no NaN)
    q0, s0 = quantize_kv(jnp.zeros((1, 1, 2, 8)))
    assert np.asarray(dequantize_kv(q0, s0)).sum() == 0.0


def test_flash_decode_int8_scales_parity():
    """Kernel in-VMEM dequant == XLA dequantize_kv + dense attention —
    the identical-expression contract the dispatches rely on."""
    rng = np.random.RandomState(3)
    B, H, L, d = 3, 4, 64, 16
    q = jnp.asarray(rng.randn(B, H, 1, d).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, L, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, L, d).astype(np.float32))
    bias = jnp.asarray(
        np.where(rng.rand(B, 1, 1, L) > 0.2, 0.0, NEG_INF).astype(np.float32)
    )
    offsets = jnp.array([0, 17, L - 1], jnp.int32)
    qk, ks = quantize_kv(k)
    qv, vs = quantize_kv(v)
    out = flash_decode(q, qk, qv, bias, offsets=offsets, k_scale=ks, v_scale=vs)
    k_pos = jnp.arange(L)[None, None, None, :]
    step = jnp.where(k_pos <= offsets[:, None, None, None], 0.0, NEG_INF)
    ref = dot_product_attention(
        q, dequantize_kv(qk, ks), dequantize_kv(qv, vs), bias + step
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


# ------------------------------------------------------- paged kernel


def _paged_fixture(rng, B, H, L, d, bs, extra_blocks=2):
    nt = L // bs
    N = B * nt + extra_blocks
    k = rng.randn(B, H, L, d).astype(np.float32)
    v = rng.randn(B, H, L, d).astype(np.float32)
    perm = rng.permutation(B * nt)
    bt = np.zeros((B, nt), np.int32)
    k_pool = np.zeros((N, H, bs, d), np.float32)
    v_pool = np.zeros((N, H, bs, d), np.float32)
    for b in range(B):
        for t in range(nt):
            blk = int(perm[b * nt + t])
            bt[b, t] = blk
            k_pool[blk] = k[b, :, t * bs : (t + 1) * bs, :]
            v_pool[blk] = v[b, :, t * bs : (t + 1) * bs, :]
    return k, v, k_pool, v_pool, bt, N


def test_flash_decode_paged_matches_flat():
    """The block-table kernel (scalar-prefetch indexed pool blocks) is
    bit-identical to flash_decode over the flattened view of the same
    blocks — scrambled block order and all."""
    rng = np.random.RandomState(4)
    B, H, L, d, bs = 3, 4, 64, 16, 16
    k, v, k_pool, v_pool, bt, N = _paged_fixture(rng, B, H, L, d, bs)
    q = jnp.asarray(rng.randn(B, H, 1, d).astype(np.float32))
    bias = jnp.asarray(
        np.where(rng.rand(B, 1, 1, L) > 0.2, 0.0, NEG_INF).astype(np.float32)
    )
    offsets = jnp.array([0, 30, L - 1], jnp.int32)
    # same tile size on both sides: the online softmax accumulates in
    # tile order, so bit-identity is a same-tiling property
    flat = flash_decode(
        q, jnp.asarray(k), jnp.asarray(v), bias, offsets=offsets, block_k=bs
    )
    paged = flash_decode_paged(
        q, jnp.asarray(k_pool), jnp.asarray(v_pool), bias,
        block_tables=jnp.asarray(bt), offsets=offsets,
    )
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(flat))
    # sentinel (unallocated) tiles beyond each row's offset change nothing
    bt2 = bt.copy()
    for b in range(B):
        for t in range(L // bs):
            if t * bs > int(offsets[b]):
                bt2[b, t] = N
    paged2 = flash_decode_paged(
        q, jnp.asarray(k_pool), jnp.asarray(v_pool), bias,
        block_tables=jnp.asarray(bt2), offsets=offsets,
    )
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(paged2))


def test_flash_decode_paged_int8_compose():
    """int8 scales compose with paging: pool-resident s8 blocks + scale
    blocks reproduce the flat int8 kernel exactly."""
    rng = np.random.RandomState(5)
    B, H, L, d, bs = 2, 2, 32, 16, 8
    k, v, k_pool, v_pool, bt, N = _paged_fixture(rng, B, H, L, d, bs)
    qk, ks = quantize_kv(jnp.asarray(k))
    qv, vs = quantize_kv(jnp.asarray(v))
    nt = L // bs
    kqp = np.zeros((N, H, bs, d), np.int8)
    vqp = np.zeros((N, H, bs, d), np.int8)
    ksp = np.zeros((N, H, bs), np.float32)
    vsp = np.zeros((N, H, bs), np.float32)
    for b in range(B):
        for t in range(nt):
            blk = int(bt[b, t])
            kqp[blk] = np.asarray(qk)[b, :, t * bs : (t + 1) * bs, :]
            vqp[blk] = np.asarray(qv)[b, :, t * bs : (t + 1) * bs, :]
            ksp[blk] = np.asarray(ks)[b, :, t * bs : (t + 1) * bs]
            vsp[blk] = np.asarray(vs)[b, :, t * bs : (t + 1) * bs]
    q = jnp.asarray(rng.randn(B, H, 1, d).astype(np.float32))
    offsets = jnp.array([7, L - 1], jnp.int32)
    flat = flash_decode(
        q, qk, qv, offsets=offsets, k_scale=ks, v_scale=vs, block_k=bs
    )
    paged = flash_decode_paged(
        q, jnp.asarray(kqp), jnp.asarray(vqp),
        block_tables=jnp.asarray(bt), offsets=offsets,
        k_scale_pool=jnp.asarray(ksp), v_scale_pool=jnp.asarray(vsp),
    )
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(flat))


# ------------------------------------------------------- engine parity


def _llama_requests(rng, n=8, lo=3, hi=14):
    return [list(rng.randint(4, 120, rng.randint(lo, hi))) for _ in range(n)]


def _engine(lm, *, is_seq2seq, W, L, slots=2, **kw):
    return ServingEngine(
        lm.module, lm.config, None,
        ServeConfig(
            max_slots=slots, prefill_batch=slots, max_new_tokens=L,
            max_source_length=W, log_every_steps=0, request_spans=False, **kw,
        ),
        is_seq2seq=is_seq2seq,
    )


@pytest.fixture(scope="module")
def llama_runs():
    """One flat-f32 llama serving run shared by the parity tests."""
    lm = load_model("llama-test")
    params = lm.init_params(0)
    rng = np.random.RandomState(7)
    reqs = _llama_requests(rng)
    W, L = 16, 8
    eng = _engine(lm, is_seq2seq=False, W=W, L=L)
    outs = eng.generate(params, reqs)
    return lm, params, reqs, W, L, eng, outs


def test_engine_paged_bucketed_bit_identical(llama_runs):
    """THE acceptance pin: paged + bucketed admission produces tokens
    BIT-identical to the flat full-width f32 engine, while the pool
    drains to zero at the end (evict returned every block) and bytes per
    live token drop (blocks track actual prompt length, not max)."""
    lm, params, reqs, W, L, flat_eng, flat = llama_runs
    eng = _engine(
        lm, is_seq2seq=False, W=W, L=L,
        paged_kv=True, kv_block_size=8, prefill_buckets=(8,),
    )
    outs = eng.generate(params, reqs)
    assert outs == flat
    assert eng.pool.blocks_in_use == 0
    assert (
        eng.last_stats.bytes_per_live_token
        < flat_eng.last_stats.bytes_per_live_token
    )


def test_engine_paged_default_block_size(llama_runs):
    """kv_block_size=0 (the CLI default) must construct: the auto block
    divides gcd(cache width, every admission bucket) — auto_block(W+L)
    alone is wrong whenever it doesn't divide W (here auto_block(24)=0
    and 24 itself doesn't tile the W=16 bucket).  Still bit-identical."""
    lm, params, reqs, W, L, _, flat = llama_runs
    eng = _engine(lm, is_seq2seq=False, W=W, L=L, paged_kv=True)
    assert (W + L) % eng.block_size == 0
    for b in eng.buckets:
        assert b % eng.block_size == 0
    assert eng.generate(params, reqs) == flat


def test_engine_paged_admit_refusal_small_pool(llama_runs):
    """A pool sized below the workload's concurrency DEFERS admissions
    (free list short) instead of over-committing — every request still
    completes with identical tokens once evictions free blocks."""
    lm, params, reqs, W, L, _, flat = llama_runs
    worst = cache_pool.blocks_needed(W, L, 8)
    eng = _engine(
        lm, is_seq2seq=False, W=W, L=L,
        paged_kv=True, kv_block_size=8, pool_blocks=worst,
    )
    outs = eng.generate(params, reqs)
    assert outs == flat
    assert eng.last_stats.admit_deferrals > 0
    assert eng.pool.blocks_in_use == 0
    # an unservable pool is rejected at construction, not livelocked
    with pytest.raises(ValueError, match="worst-case request"):
        _engine(
            lm, is_seq2seq=False, W=W, L=L,
            paged_kv=True, kv_block_size=8, pool_blocks=worst - 1,
        )


def test_engine_pool_garbage_invariant(llama_runs):
    """Stale-block-unreachable, restated per block (the PR 7 slot-reuse
    argument): poison the ENTIRE pool at init — every block then behaves
    like a freed block full of a previous owner's data — and the engine
    still produces the flat engine's exact tokens, because every read is
    masked to the owner's written region."""
    lm, params, reqs, W, L, _, flat = llama_runs
    eng = _engine(lm, is_seq2seq=False, W=W, L=L, paged_kv=True, kv_block_size=8)
    orig = eng._init_state

    def poisoned(p):
        st = orig(p)
        st["pool"] = jax.tree.map(
            lambda x: jnp.full(x.shape, 1e3, x.dtype) if x.ndim >= 3 else x,
            st["pool"],
        )
        return st

    eng._init_state = poisoned
    assert eng.generate(params, reqs) == flat


def test_engine_int8_all_flags_vs_static(llama_runs):
    """Determinism under ALL THREE flags combined: the int8+paged+bucketed
    engine is token-identical to the static int8 runner (same quantized
    cache on both sides), and zero programs retrace across a second full
    admit/evict/bucket churn (AOT-warmed, compile-count pinned)."""
    lm, params, reqs, W, L, _, _ = llama_runs
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    static8 = static_batch_generate(
        lm.module, lm.config, None, params, reqs,
        max_new_tokens=L, width=W, batch=2, is_seq2seq=False,
        kv_cache_dtype="int8",
    )
    eng = _engine(
        lm, is_seq2seq=False, W=W, L=L,
        kv_cache_dtype="int8", paged_kv=True, kv_block_size=8,
        prefill_buckets=(8,),
    )
    outs = eng.generate(params, reqs)
    for got, want in zip(outs, static8):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)
    # one trace per bucket for prefill/admit, ONE decode step — and no
    # retrace on a second serve over the same engine
    assert eng.trace_counts == {"prefill": 2, "admit": 2, "decode_step": 1}
    eng.generate(params, reqs)
    assert eng.trace_counts == {"prefill": 2, "admit": 2, "decode_step": 1}


def test_engine_int8_token_match_rates(llama_runs):
    """The int8 tolerance contract: engine-int8 vs engine-f32 greedy
    token match.  t5-test holds the >= 0.99 bar; llama-test's random-init
    logits are near-uniform (the argmax-stability worst case — one
    near-tie flip cascades through the greedy prefix), so it pins the
    measured-with-margin rate plus the BIT-exact engine==static-int8
    determinism above.  Real checkpoints with confident logits sit at the
    >= 0.99 contract (README 'Serving capacity')."""
    lm, params, reqs, W, L, _, flat = llama_runs
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id

    def match_rate(a_rows, b_rows):
        match = total = 0
        for a, b in zip(a_rows, b_rows):
            ta, tb = trim_eos(a, eos, pad), trim_eos(b, eos, pad)
            n = min(len(ta), len(tb))
            total += max(len(ta), len(tb))
            match += sum(x == y for x, y in zip(ta[:n], tb[:n]))
        return match / max(total, 1)

    i8 = _engine(lm, is_seq2seq=False, W=W, L=L, kv_cache_dtype="int8")
    assert match_rate(flat, i8.generate(params, reqs)) >= 0.85
    # int8 footprint: the static account matches the closed form
    # 4D/(D+4) exactly (s8 buffers + one f32 scale per D-row); >= 3.5x
    # needs head_dim >= 64 — the production shapes — while the D=16 test
    # models land at exactly 3.2x
    d = lm.config.hidden_size // lm.config.num_attention_heads
    flat_eng = llama_runs[5]
    ratio = (
        flat_eng.last_stats.cache_bytes_resident
        / i8.last_stats.cache_bytes_resident
    )
    want = 4 * d / (d + 4)
    assert ratio == pytest.approx(want, rel=0.02)
    assert 4 * 64 / (64 + 4) >= 3.5  # the production head-dim claim

    # the seq2seq test model carries the >= 0.99 pin
    lm2 = load_model("t5-test")
    p2 = lm2.init_params(0)
    rng = np.random.RandomState(11)
    reqs2 = [list(rng.randint(4, 200, rng.randint(4, 28))) for _ in range(6)]
    e_f32 = _engine(lm2, is_seq2seq=True, W=32, L=8)
    e_i8 = _engine(lm2, is_seq2seq=True, W=32, L=8, kv_cache_dtype="int8")
    eos, pad = lm2.config.eos_token_id, lm2.config.pad_token_id
    assert match_rate(e_f32.generate(p2, reqs2), e_i8.generate(p2, reqs2)) >= 0.99


def test_engine_sustained_pool_pressure_no_starvation(llama_runs):
    """ISSUE 15 satellite: admit-deferral under SUSTAINED pool pressure —
    3x the fixture's load through a minimal pool (one worst-case request)
    — defers continually but eventually completes EVERY request with the
    flat engine's exact tokens (no starvation: FIFO admission means a
    deferred request admits as soon as evictions fund it), and the pool
    drains to empty."""
    lm, params, _, W, L, flat_eng, _ = llama_runs
    rng = np.random.RandomState(3)
    reqs = _llama_requests(rng, n=24)
    # the flat fixture engine's programs are already compiled: its run is
    # the completeness+correctness oracle at zero extra trace cost
    flat = flat_eng.generate(params, reqs)
    worst = cache_pool.blocks_needed(W, L, 8)
    eng = _engine(
        lm, is_seq2seq=False, W=W, L=L,
        paged_kv=True, kv_block_size=8, pool_blocks=worst,
    )
    outs = eng.generate(params, reqs)
    assert outs == flat
    assert all(len(o) >= 1 for o in outs)  # every request produced output
    # pressure was genuinely sustained, not a one-off dip
    assert eng.last_stats.admit_deferrals >= 5
    assert eng.pool.blocks_in_use == 0


def test_engine_pool_blocks_all_returned_random_churn(llama_runs):
    """ISSUE 15 satellite: evict-on-done returns EVERY pool block under
    randomized admit/evict churn — random prompt lengths and budgets
    over several waves on one engine; after each wave the free list
    holds exactly the full block set (leak AND double-free would both
    break the set equality)."""
    lm, params, _, W, L, _, _ = llama_runs
    eng = _engine(lm, is_seq2seq=False, W=W, L=L, paged_kv=True, kv_block_size=8)
    all_blocks = set(range(eng.pool.num_blocks))
    rng = np.random.RandomState(11)
    for wave in range(3):
        reqs = _llama_requests(rng, n=10, lo=3, hi=14)
        budgets = [int(b) for b in rng.randint(1, L + 1, len(reqs))]
        eng.generate(params, reqs, max_new=budgets)
        assert eng.pool.blocks_in_use == 0, f"wave {wave} leaked blocks"
        assert set(eng.pool._free) == all_blocks, f"wave {wave} corrupted free list"


def test_engine_seq2seq_buckets_identical_and_warm():
    """Bucketed admission on the seq2seq engine: identical tokens to the
    single-width engine, one compiled prefill/admit per bucket (all
    AOT-warmed at first generate), capacity gauges in the summary."""
    lm = load_model("t5-test")
    params = lm.init_params(0)
    rng = np.random.RandomState(13)
    reqs = [list(rng.randint(4, 200, rng.randint(4, 28))) for _ in range(6)]
    flat = _engine(lm, is_seq2seq=True, W=32, L=8).generate(params, reqs)
    eng = _engine(lm, is_seq2seq=True, W=32, L=8, prefill_buckets=(8, 16))
    outs = eng.generate(params, reqs)
    assert outs == flat
    assert eng.trace_counts == {"prefill": 3, "admit": 3, "decode_step": 1}
    assert eng.last_stats.cache_bytes_resident > 0
    assert eng.last_stats.bytes_per_live_token > 0


def test_engine_rejects_bad_capacity_configs():
    lm = load_model("t5-test", load_weights=False)
    with pytest.raises(ValueError, match="f32.*int8|'f32' or 'int8'"):
        _engine(lm, is_seq2seq=True, W=32, L=8, kv_cache_dtype="fp8")
    with pytest.raises(ValueError, match="paged_kv applies to the causal"):
        _engine(lm, is_seq2seq=True, W=32, L=8, paged_kv=True)
    clm = load_model("llama-test", load_weights=False)
    with pytest.raises(ValueError, match="does not tile"):
        _engine(clm, is_seq2seq=False, W=16, L=8, paged_kv=True, kv_block_size=7)
    with pytest.raises(ValueError, match="not a multiple of the kv block"):
        _engine(
            clm, is_seq2seq=False, W=16, L=8,
            paged_kv=True, kv_block_size=8, prefill_buckets=(12,),
        )


# ------------------------------------------------------- spec lint / rules


def test_int8_cache_scale_leaves_lint_green():
    """CACHE_RULES covers the int8 cache's scale leaves: the lint is green
    on the quantized abstract cache, and a rule set WITHOUT the scale rule
    errors on every scale leaf (unmatched-cache-leaf — the strengthened
    3-D check)."""
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.analysis.spec_lint import lint_cache_sharding
    from distributed_llms_example_tpu.evaluation.generation import abstract_cache
    from distributed_llms_example_tpu.parallel.sharding import ShardingRules

    axes = {"data": 2, "fsdp": 2, "tensor": 2}
    for name, seq2seq in (("t5-test", True), ("llama-test", False)):
        lm = load_model(name, load_weights=False)
        a_params = jax.eval_shape(lambda lm=lm: lm.init_params(0))
        cache = abstract_cache(
            lm.module, a_params, batch=8, max_new_tokens=16, src_len=32,
            is_seq2seq=seq2seq, kv_cache_dtype="int8",
        )
        leaves = jax.tree.leaves(cache)
        assert any(getattr(x, "dtype", None) == jnp.int8 for x in leaves)
        assert any(
            getattr(x, "ndim", 0) == 3 for x in leaves
        ), "int8 cache should carry (B, H, L) scale leaves"
        findings = lint_cache_sharding(cache, axes)
        errors = [f for f in findings if f.severity == "error"]
        assert not errors, errors
    # drop the scale rule → every scale leaf is an unmatched error
    lm = load_model("t5-test", load_weights=False)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    cache = abstract_cache(
        lm.module, a_params, batch=8, max_new_tokens=16, src_len=32,
        kv_cache_dtype="int8",
    )
    bad = ShardingRules(rules=[
        (r"(cached_key|cached_value)$", P(("data", "fsdp"), "tensor", None, None)),
        (r"cache_index$", P()),
    ])
    findings = lint_cache_sharding(cache, axes, rules=bad)
    assert any(
        f.code == "unmatched-cache-leaf" and "_scale" in f.message
        for f in findings
    )


def test_pool_rules_lint_and_scale_spec(mesh8):
    """POOL_RULES validates the pool tree like CACHE_RULES validates the
    flat cache (blocks never shard over batch axes, heads over tensor) —
    and kv_scale_spec resolves the scale layout on the real mesh."""
    from distributed_llms_example_tpu.analysis.spec_lint import lint_cache_sharding
    from distributed_llms_example_tpu.evaluation.generation import abstract_cache
    from distributed_llms_example_tpu.parallel.sharding import (
        cache_rules,
        kv_scale_spec,
        pool_rules,
        resolve_shardings,
    )

    lm = load_model("llama-test", load_weights=False)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    cache = abstract_cache(
        lm.module, a_params, batch=8, max_new_tokens=16, src_len=32,
        is_seq2seq=False, kv_cache_dtype="int8",
    )
    pool_tree = jax.eval_shape(lambda: cache_pool.pool_cache_tree(cache, 12, 8))
    findings = lint_cache_sharding(
        pool_tree, {"data": 2, "fsdp": 2, "tensor": 2}, rules=pool_rules()
    )
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, errors
    # scale leaves resolve on the 8-device mesh per CACHE_RULES
    sh = resolve_shardings(cache, mesh8, cache_rules())
    scales = [
        (jax.tree_util.keystr(p), s.spec)
        for p, s in jax.tree_util.tree_leaves_with_path(sh)
        if "_scale" in jax.tree_util.keystr(p)
    ]
    assert scales
    for path, spec in scales:
        assert spec[0] == ("data", "fsdp", "expert"), (path, spec)
        assert spec[1] == "tensor", (path, spec)
    # the one definition both sides derive from
    assert kv_scale_spec((8, 4, 24), dict(mesh8.shape))[1] == "tensor"


# ----------------------------------------------- prefix cache: pool unit


def test_chain_hash_collision_discipline():
    """Chained identity: a block's hash commits to its WHOLE prefix, so
    equal hash at position k implies blocks 0..k-1 matched too; token
    boundaries are part of the identity (no concatenation ambiguity);
    the partial tail block has no identity at all."""
    bs = 4
    a = cache_pool.chain_hashes([1, 2, 3, 4, 5, 6, 7, 8], bs)
    b = cache_pool.chain_hashes([9, 9, 9, 9, 5, 6, 7, 8], bs)
    assert len(a) == len(b) == 2
    # identical second-block TOKENS, different predecessor → different hash
    assert a[0] != b[0] and a[1] != b[1]
    # extending past a full block never perturbs the existing chain
    c = cache_pool.chain_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], bs)
    assert c == a  # the 1-token tail is unhashed (no stable identity)
    # boundary discipline: [1, 23] vs [12, 3] must not collide
    assert cache_pool.block_hash(None, [1, 23]) != cache_pool.block_hash(None, [12, 3])


def test_pool_register_first_writer_wins_and_acquire_errors():
    pool = cache_pool.CachePool(num_blocks=8, block_size=4)
    pool.warm_capacity = 8
    h = cache_pool.chain_hashes([1, 2, 3, 4], 4)
    b1 = pool.alloc(1)
    b2 = pool.alloc(1)
    pool.register(b1, h)
    pool.register(b2, h)  # duplicate content: first writer keeps the hash
    assert pool.lookup(h[0]) == b1[0]
    assert pool.match_chain(h) == b1
    # the anonymous duplicate reclaims to the FREE list, not the warm LRU
    pool.free(b2)
    assert pool.blocks_warm == 0
    # the registered block parks warm at refcount 0...
    pool.free(b1)
    assert pool.blocks_warm == 1 and pool.match_chain(h) == b1
    # ...and revives via acquire
    pool.acquire(b1)
    assert pool.blocks_in_use == 1 and pool.blocks_warm == 0
    pool.free(b1)
    # a chain match gone stale (block neither live nor warm) raises
    pool.drop_warm()
    with pytest.raises(ValueError, match="neither live nor warm"):
        pool.acquire(b1)


def test_pool_warm_lru_eviction_order():
    """Warm retention evicts strictly oldest-first; re-acquire refreshes
    recency; alloc pressure reclaims warm blocks before refusing; and
    drop_warm clears the whole set (the replica-death path)."""
    pool = cache_pool.CachePool(num_blocks=4, block_size=4)
    pool.warm_capacity = 2
    chains = [cache_pool.chain_hashes([i, i, i, i], 4) for i in (1, 2, 3)]
    blocks = []
    for h in chains:
        (b,) = pool.alloc(1)
        pool.register([b], h)
        blocks.append(b)
    for b in blocks:
        pool.free([b])  # park in order 0, 1, 2 — capacity 2 evicts 0
    assert pool.blocks_warm == 2
    assert pool.match_chain(chains[0]) == []
    assert pool.match_chain(chains[1]) == [blocks[1]]
    # revive 1 then re-park: now 1 is NEWEST, so pressure evicts 2 first
    pool.acquire([blocks[1]])
    pool.free([blocks[1]])
    grant = pool.alloc(3)  # 2 free + 1 evicted warm (block 2, the oldest)
    assert grant is not None
    assert pool.match_chain(chains[2]) == []
    assert pool.match_chain(chains[1]) == [blocks[1]]
    pool.free(grant)
    assert pool.drop_warm() == 1
    assert pool.blocks_warm == 0 and pool.match_chain(chains[1]) == []
    assert pool.blocks_free == pool.num_blocks


def test_pool_prefix_refcount_churn_invariant():
    """Property sweep over admit/share/free churn with warm retention:
    random sessions match-acquire-alloc-register like the engine's
    admission, free in random order — after EVERY operation the walked
    refcount invariant holds and the free/used/warm partition is exact."""
    rng = np.random.RandomState(17)
    pool = cache_pool.CachePool(num_blocks=30, block_size=4)
    pool.warm_capacity = 8
    live: list[list[int]] = []  # per-request block lists (the block tables)
    for _ in range(300):
        if live and rng.rand() < 0.45:
            chain = live.pop(rng.randint(len(live)))
            pool.free(list(reversed(chain)))
        else:
            # small alphabet → real prefix collisions across requests
            toks = [int(t) for t in rng.randint(0, 3, int(rng.randint(4, 17)))]
            hashes = cache_pool.chain_hashes(toks, 4)
            p = len(toks)
            chain = pool.match_chain(hashes[: (p - 1) // 4])
            k = len(chain)
            need = max(1, -(-p // 4)) - k + 1  # + one decode block
            if k:
                pool.acquire(chain)
            fresh = pool.alloc(need)
            if fresh is None:
                if k:
                    pool.free(list(reversed(chain)))  # transactional rollback
                continue
            blocks = chain + fresh
            full = p // 4
            if full:
                pool.register(blocks[:full], hashes[:full])
            live.append(blocks)
        assert pool.ref_invariant_violations(live) == []
        assert pool.blocks_free + pool.blocks_in_use == 30
    for chain in live:
        pool.free(list(reversed(chain)))
    assert pool.ref_invariant_violations([]) == []
    assert pool.blocks_in_use == 0


# ----------------------------------------------- prefix cache: engine


def _prefix_engine(lm, W, L, **kw):
    kw.setdefault("pool_blocks", 24)  # headroom: warm retention lives in it
    return _engine(
        lm, is_seq2seq=False, W=W, L=L,
        paged_kv=True, kv_block_size=8,
        prefix_cache=True, prefix_cache_budget_gib=0.25, **kw,
    )


def _chat_requests(rng, sys_len=8, n=8, lo=2, hi=8):
    sys_toks = [int(t) for t in rng.randint(4, 120, sys_len)]
    return [
        sys_toks + [int(t) for t in rng.randint(4, 120, rng.randint(lo, hi))]
        for _ in range(n)
    ]


def test_engine_prefix_warm_vs_cold_bit_identical(llama_runs):
    """THE warm-path acceptance pin (greedy): shared-prefix requests
    through the prefix cache produce tokens BIT-identical to the flat
    cold engine, with real hits (the shared system-prompt block prefills
    once), an exact reuse ledger, and a drained pool whose warm set
    holds exactly the one registered chain block.  A SECOND session on
    the same engine drops the stale warm set (its device pool was
    re-zeroed) and is bit-identical again — no cross-session splice."""
    lm, params, _, W, L, flat_eng, _ = llama_runs
    rng = np.random.RandomState(23)
    reqs = _chat_requests(rng)
    flat = flat_eng.generate(params, reqs)
    eng = _prefix_engine(lm, W, L)
    outs = eng.generate(params, reqs)
    assert outs == flat
    st = eng.last_stats
    # every request was eligible; all but the first matched the shared
    # 8-token system block (pool headroom keeps it warm/live throughout)
    assert st.prefix_lookups == len(reqs)
    assert st.prefix_hits == len(reqs) - 1
    assert st.prefill_tokens_saved == (len(reqs) - 1) * 8
    assert st.prefill_tokens_total == sum(len(r) for r in reqs)
    assert eng.pool.blocks_in_use == 0
    # all requests share ONE full block (the system prompt): first writer
    # wins, so exactly one block is registered and retained warm
    assert eng.pool.blocks_warm == 1
    # compiled-program budget: one warm_admit per bucket, nothing retraced
    assert eng.trace_counts == {
        "prefill": 1, "admit": 1, "warm_admit": 1, "decode_step": 1,
    }
    outs2 = eng.generate(params, reqs)
    assert outs2 == flat
    assert eng.last_stats.prefix_hits == len(reqs) - 1
    assert eng.trace_counts == {
        "prefill": 1, "admit": 1, "warm_admit": 1, "decode_step": 1,
    }


def test_engine_prefix_cow_divergence_and_slot_reuse(llama_runs):
    """COW discipline through divergence and slot reuse, stepwise: A and
    B share one system block then diverge (B admits warm, holding the
    SHARED block and allocating only its own tail — never writing the
    shared block); C repeats A exactly and re-acquires A's chain from
    the warm LRU through a REUSED slot.  Tokens bit-identical to cold
    throughout, and the walked refcount invariant holds after every
    step."""
    lm, params, _, W, L, flat_eng, _ = llama_runs
    rng = np.random.RandomState(29)
    sys_toks = [int(t) for t in rng.randint(4, 120, 8)]
    a = sys_toks + [int(t) for t in rng.randint(4, 120, 5)]
    b = sys_toks + [int(t) for t in rng.randint(4, 120, 5)]
    reqs = [a, b, list(a)]
    flat = flat_eng.generate(params, reqs)
    eng = _prefix_engine(lm, W, L)
    sess = eng.open(params)
    for r in reqs:
        sess.submit(r)
    shared_in_use = None
    while sess.has_work():
        sess.step()
        assert sess.prefix_ref_violations() == []
        if shared_in_use is None and sess.active.all():
            # A and B live together: 3 blocks each (2 prompt + 1 decode)
            # MINUS the one shared system block
            shared_in_use = eng.pool.blocks_in_use
    sess.finalize()
    assert shared_in_use == 5
    assert list(sess.outputs) == flat
    st = eng.last_stats
    # B matched the system block; C matched its full chain (1 block —
    # the last prompt block always re-prefills for first-token logits)
    assert st.prefix_hits == 2
    assert st.prefix_lookups == 3
    assert eng.pool.blocks_in_use == 0
    assert eng.pool.ref_invariant_violations([]) == []


def test_engine_prefix_custom_mask_ineligible(llama_runs):
    """A request with a custom attention mask has no token-only identity:
    it neither matches nor registers (zero lookups), and tokens stay
    bit-identical to the flat engine under the same masks."""
    lm, params, _, W, L, flat_eng, _ = llama_runs
    rng = np.random.RandomState(31)
    reqs = _chat_requests(rng, n=4)
    masks = [[1] * len(r) for r in reqs]
    flat = flat_eng.generate(params, reqs, attention_masks=masks)
    eng = _prefix_engine(lm, W, L)
    outs = eng.generate(params, reqs, attention_masks=masks)
    assert outs == flat
    st = eng.last_stats
    assert st.prefix_lookups == 0 and st.prefix_hits == 0
    assert eng.pool.blocks_warm == 0  # nothing registered, nothing retained
    assert eng.pool.blocks_in_use == 0


def test_engine_prefix_warm_beam_bit_identical(llama_runs):
    """Beam-search leg of the bit-identity contract: a KV prefix
    reconstructed from WARM pool blocks matches a cold prefill's cache
    region (to the cross-program ulp — the engine's compiled prefill
    and the generator's eager one fuse differently, the same class of
    difference the engine-vs-static token pins absorb), and a
    num_beams=2 decode over the spliced carry emits exactly the cold
    run's beam tokens — the warm path changes where prefix KV comes
    from, never what it holds."""
    from distributed_llms_example_tpu.evaluation.generation import CausalGenerator

    lm, params, _, W, L, _, _ = llama_runs
    rng = np.random.RandomState(37)
    prompt = [int(t) for t in rng.randint(4, 120, 12)]
    eng = _prefix_engine(lm, W, L)
    sess = eng.open(params)
    sess.submit(list(prompt))
    while sess.has_work():
        sess.step()
    sess.finalize()
    # the finished request's full-block chain is warm and matchable
    hashes = cache_pool.chain_hashes(prompt, eng.block_size)
    chain = eng.pool.match_chain(hashes[: (len(prompt) - 1) // eng.block_size])
    assert len(chain) == 1
    bt = np.full((1, eng.n_tiles), eng.pool.num_blocks, np.int32)
    bt[0, : len(chain)] = chain
    warm_view = cache_pool.gather_cache(sess.state["pool"], jnp.asarray(bt))
    # cold reference: the generator's own prefill at the same width
    gen = CausalGenerator(lm.module, lm.config, L, num_beams=2)
    ids = np.full((1, W), lm.config.pad_token_id, np.int32)
    mask = np.zeros((1, W), np.int32)
    ids[0, : len(prompt)] = prompt
    mask[0, : len(prompt)] = 1
    carry_cold = gen.prefill(params, jnp.asarray(ids), jnp.asarray(mask))
    kbs = len(chain) * eng.block_size
    for cold, warm in zip(
        jax.tree.leaves(carry_cold["cache"]), jax.tree.leaves(warm_view)
    ):
        if getattr(cold, "ndim", 0) == 4:
            # warm pool bytes ≈ cold prefill bytes over the cached prefix
            # (exact within one program; here across two compilations)
            np.testing.assert_allclose(
                np.asarray(warm)[0, :, :kbs, :],
                np.asarray(cold)[0, :, :kbs, :], atol=1e-5,
            )

    def splice(c, w):
        if getattr(c, "ndim", 0) == 4 and c.shape[-1] == w.shape[-1]:
            rep = jnp.repeat(w[:, :, :kbs, :], 2, axis=0)  # K beams share it
            return c.at[:, :, :kbs, :].set(rep)
        return c

    carry_warm = dict(carry_cold)
    carry_warm["cache"] = jax.tree.map(splice, carry_cold["cache"], warm_view)
    out_cold = np.asarray(gen.finalize(gen.decode_loop(params, carry_cold)))
    out_warm = np.asarray(gen.finalize(gen.decode_loop(params, carry_warm)))
    np.testing.assert_array_equal(out_warm, out_cold)
