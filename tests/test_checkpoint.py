"""Checkpoint/resume + Valohai sidecar tests."""

import json
import os

import jax
import numpy as np

from distributed_llms_example_tpu.io.checkpoint import Checkpointer, abstract_like
from distributed_llms_example_tpu.io.valohai_meta import (
    dataset_version_metadata,
    get_run_identification,
    save_valohai_metadata,
)
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.parallel.sharding import shard_params
from distributed_llms_example_tpu.train.optim import make_optimizer
from distributed_llms_example_tpu.train.step import create_train_state, state_shardings


def _make_state(mesh):
    lm = load_model("t5-test")
    tx, _ = make_optimizer()
    params = shard_params(jax.device_get(lm.init_params(0)), mesh)
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh), sh


def test_save_restore_roundtrip(tmp_path, mesh8):
    state, sh = _make_state(mesh8)
    ck = Checkpointer(str(tmp_path / "ckpt"), save_every_steps=10, async_save=False)
    ck.save(10, state)
    ck.save(20, state.replace(step=state.step + 20))
    ck.wait()
    assert ck.latest_step() == 20
    restored, step = ck.restore_latest(abstract_like(state, sh))
    assert step == 20
    assert int(jax.device_get(restored.step)) == 20
    a = jax.tree.leaves(jax.device_get(state.params))
    b = jax.tree.leaves(jax.device_get(restored.params))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # restored arrays carry the mesh shardings
    leaf = restored.params["shared"]["embedding"]
    assert leaf.sharding == state.params["shared"]["embedding"].sharding
    ck.close()


def test_restore_latest_none_when_empty(tmp_path, mesh8):
    state, sh = _make_state(mesh8)
    ck = Checkpointer(str(tmp_path / "empty"), async_save=False)
    assert ck.restore_latest(abstract_like(state, sh)) is None
    ck.close()


def test_retention(tmp_path, mesh8):
    state, _ = _make_state(mesh8)
    ck = Checkpointer(str(tmp_path / "keep"), save_every_steps=1, keep=2, async_save=False)
    for s in (1, 2, 3, 4):
        ck.save(s, state.replace(step=state.step * 0 + s))
    ck.wait()
    assert ck.latest_step() == 4
    steps = sorted(ck.manager.all_steps())
    assert steps == [3, 4]
    ck.close()


def test_should_save_cadence(tmp_path):
    ck = Checkpointer(str(tmp_path / "c"), save_every_steps=50, async_save=False)
    assert ck.should_save(50) and ck.should_save(100)
    assert not ck.should_save(51)
    ck.close()
    ck2 = Checkpointer(str(tmp_path / "c2"), save_every_steps=0, async_save=False)
    assert not ck2.should_save(50)  # end-of-training-only mode
    ck2.close()


def test_run_identification_fallback(tmp_path):
    project, exec_id = get_run_identification(str(tmp_path / "missing.json"))
    assert project == "test" and exec_id.isdigit()


def test_run_identification_from_config(tmp_path):
    cfg = tmp_path / "execution.json"
    cfg.write_text(json.dumps({"valohai.project-name": "org/my-proj", "valohai.execution-id": "abc123"}))
    assert get_run_identification(str(cfg)) == ("my-proj", "abc123")
    md = dataset_version_metadata(str(cfg))
    ver = md["valohai.dataset-versions"][0]
    assert ver["uri"] == "dataset://llm-models/my-proj_abc123"
    assert ver["valohai.tags"] == ["dev", "llm"]
    assert ver["targeting_aliases"][0].startswith("dev-") and ver["targeting_aliases"][0].endswith("-model")


def test_sidecars_written_and_idempotent(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    (out / "model.safetensors").write_bytes(b"x")
    (out / "config.json").write_text("{}")
    written = save_valohai_metadata(str(out), str(tmp_path / "missing.json"))
    assert sorted(os.path.basename(p) for p in written) == [
        "config.json.metadata.json",
        "model.safetensors.metadata.json",
    ]
    # second call must not produce .metadata.json.metadata.json
    written2 = save_valohai_metadata(str(out), str(tmp_path / "missing.json"))
    assert len(written2) == 2
    assert len(list(out.iterdir())) == 4
