"""Quantized gradient collectives (--grad-compression int8): wire math,
error-feedback contracts, composition, and the compiled-program proofs.

Pure-function tests run the reduction with ``mesh=None`` (identical math,
no sharding pins); compiled tests ride the 8-device mesh fixtures — the
data=2 x fsdp=2 x tensor=2 mesh exercises the worker tiling against both
model-sharding axes, and the data=8 mesh is where the census A/B reads
cleanest (the replica leg IS the whole gradient reduction there)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.ops.quant_collectives import (
    GRAD_WORKER_AXES,
    block_size_for,
    dequantize_blocks,
    error_feedback_shardings,
    error_feedback_specs,
    quantize_blocks,
    quantized_tree_reduce,
    stochastic_round,
    tiled_spec,
    worker_count,
    zero_error_feedback,
)
from distributed_llms_example_tpu.parallel.sharding import shard_params
from distributed_llms_example_tpu.train.optim import make_optimizer
from distributed_llms_example_tpu.train.step import (
    create_train_state,
    make_train_step,
    put_batch,
    state_shardings,
)


def _toy_batch(b=8, src=16, tgt=8, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
    attn = np.ones((b, src), np.int32)
    labels = rng.randint(2, vocab, (b, tgt)).astype(np.int32)
    labels[:, -2:] = LABEL_PAD
    return {"input_ids": input_ids, "attention_mask": attn, "labels": labels}


# ---------------------------------------------------------------------------
# pure wire math (mesh=None: same code path, no sharding pins)
# ---------------------------------------------------------------------------


def test_quantize_roundtrip_error_bound():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 8, 256)) * 3.0
    q, scale = quantize_blocks(x, key, block=64)
    assert q.dtype == jnp.int8
    deq = dequantize_blocks(q, scale[None], block=64)
    # stochastic rounding error is strictly under one quantization step
    step = np.repeat(np.asarray(scale), 64, axis=-1)[None]
    assert np.all(np.abs(np.asarray(deq - x)) <= step + 1e-7)


def test_stochastic_rounding_unbiased():
    v = jnp.asarray([0.25, -1.75, 3.5, -0.01])
    keys = jax.random.split(jax.random.PRNGKey(1), 4000)
    samples = jax.vmap(lambda k: stochastic_round(v, k))(keys)
    mean = np.asarray(jnp.mean(samples, axis=0))
    np.testing.assert_allclose(mean, np.asarray(v), atol=0.05)


def test_integer_sum_order_free():
    """Shared scales + int32 tile sums: permuting the worker order changes
    nothing, bit for bit — the determinism float reductions cannot give."""
    key = jax.random.PRNGKey(2)
    g = jax.random.normal(key, (4, 2, 256))
    q, scale = quantize_blocks(g, key, block=256)
    s1 = jnp.sum(q.astype(jnp.int32), axis=0)
    s2 = jnp.sum(q[::-1].astype(jnp.int32), axis=0)
    assert np.array_equal(np.asarray(s1), np.asarray(s2))


def test_reduce_matches_true_sum_within_bound():
    key = jax.random.PRNGKey(3)
    g = {"w": jax.random.normal(key, (4, 16, 512))}
    ef = zero_error_feedback({"w": jnp.zeros((16, 512))}, 4)
    out, new_ef = quantized_tree_reduce(g, ef, key)
    true = np.asarray(jnp.sum(g["w"], axis=0))
    got = np.asarray(out["w"])
    # worst case: W per-worker quantization steps of error per element
    q, scale = quantize_blocks(g["w"], key, block=256)
    bound = 4 * np.repeat(np.asarray(scale), 256, axis=-1) + 1e-6
    assert np.all(np.abs(got - true) <= bound)
    assert float(jnp.max(jnp.abs(new_ef["w"]))) > 0.0


def test_error_feedback_telescopes():
    """Sum of applied (reduced) gradients over K steps == sum of true
    gradient sums, up to the FINAL residual — the EF contract: no error
    is ever lost, only deferred one step."""
    key = jax.random.PRNGKey(4)
    W, shape = 4, (8, 256)
    ef = zero_error_feedback({"w": jnp.zeros(shape)}, W)
    applied = np.zeros(shape, np.float64)
    true = np.zeros(shape, np.float64)
    for k in range(5):
        g = {"w": jax.random.normal(jax.random.fold_in(key, k), (W,) + shape)}
        out, ef = quantized_tree_reduce(
            g, ef, jax.random.fold_in(key, 100 + k)
        )
        applied += np.asarray(out["w"], np.float64)
        true += np.asarray(jnp.sum(g["w"], axis=0), np.float64)
    residual = np.asarray(jnp.sum(ef["w"], axis=0), np.float64)
    np.testing.assert_allclose(applied + residual, true, atol=2e-4)


def test_small_leaves_take_fp32_fallback():
    key = jax.random.PRNGKey(5)
    g = {"scale": jax.random.normal(key, (4, 64))}  # 64 elems << floor
    ef = zero_error_feedback({"scale": jnp.zeros((64,))}, 4)
    out, new_ef = quantized_tree_reduce(g, ef, key)
    np.testing.assert_allclose(
        np.asarray(out["scale"]), np.asarray(jnp.sum(g["scale"], axis=0)),
        rtol=1e-6,
    )
    assert float(jnp.max(jnp.abs(new_ef["scale"]))) == 0.0


def test_block_size_respects_shards():
    assert block_size_for(512, 1) == 256
    assert block_size_for(512, 2) == 256
    assert block_size_for(512, 4) == 128  # per-shard extent caps the block
    assert block_size_for(12, 1) == 12
    assert block_size_for(7, 1) == 7


# ---------------------------------------------------------------------------
# layout contracts: tiled specs, EF mirror lint
# ---------------------------------------------------------------------------


def test_tiled_spec_prefixes_workers():
    assert tiled_spec(P("fsdp", "tensor")) == P("data", "fsdp", "tensor")
    assert tiled_spec(P()) == P("data")
    tree = error_feedback_specs({"a": P(("tensor", "fsdp"), None)})
    assert tree["a"] == P("data", ("tensor", "fsdp"), None)


def test_ef_mirror_lint_green_and_seeded_violation(monkeypatch):
    from distributed_llms_example_tpu.analysis import spec_lint
    from distributed_llms_example_tpu.ops import quant_collectives

    lm = load_model("t5-test", load_weights=False)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    assert spec_lint.lint_error_feedback_mirror(a_params) == []

    # seed a drift: an EF layout that re-shards the residual against the
    # tiled gradients (drops the param spec's first entry)
    def drifted(spec_tree):
        return jax.tree.map(
            lambda s: P("data", *([None] + list(s[1:]) if len(s) else [])),
            spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    monkeypatch.setattr(quant_collectives, "error_feedback_specs", drifted)
    findings = spec_lint.lint_error_feedback_mirror(a_params)
    assert any(f.code == "error-feedback-spec-mismatch" for f in findings)


def test_composition_rows():
    from distributed_llms_example_tpu.analysis.composition import (
        check_composition,
        config_flags,
        failing_combos,
    )

    flags = config_flags(pipelined=False, grad_compression="int8")
    assert "grad_compression" in flags
    assert config_flags(pipelined=False, grad_compression="off") == set()
    # pipelined: bad
    bad = failing_combos(
        family="llama", schedule="gpipe",
        mesh_axes={"stage": 2, "data": 2},
        flags=("grad_compression", "pipelined"),
    )
    assert any(r.id == "grad-compression-pipelined" for r in bad)
    # sequence: bad
    bad = failing_combos(
        family="llama", mesh_axes={"sequence": 2, "data": 2},
        flags=("grad_compression",),
    )
    assert any(r.id == "grad-compression-sequence" for r in bad)
    # gspmd data x fsdp: no failing row
    assert not failing_combos(
        family="t5", mesh_axes={"data": 2, "fsdp": 4},
        flags=("grad_compression",),
    )
    assert not check_composition(
        family="t5", mesh_axes={"data": 2, "fsdp": 4},
        flags=("grad_compression", "grad_accum"),
    )


def test_make_train_step_guards():
    lm = load_model("t5-test", load_weights=False)
    tx, schedule = make_optimizer(total_steps=10)
    with pytest.raises(ValueError, match="grad_compression"):
        make_train_step(
            lm.module, lm.config, tx, schedule, None, grad_compression="int4"
        )


# ---------------------------------------------------------------------------
# the compiled step (mesh8 = data2 x fsdp2 x tensor2)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def t5():
    lm = load_model("t5-test")
    params = jax.device_get(lm.init_params(0))
    return lm, params


def _build(lm, params, mesh, mode, accum=1, lr=1e-3):
    tx, schedule = make_optimizer(
        learning_rate=lr, warmup_steps=0, total_steps=1000
    )
    state = create_train_state(
        shard_params(params, mesh), tx,
        grad_compression=mode, workers=worker_count(dict(mesh.shape)),
    )
    sh = state_shardings(state, mesh)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh,
        grad_accum_steps=accum, grad_compression=mode, donate=False,
    )
    step, _ = build(state)
    return step, state, sh


@pytest.fixture(scope="module")
def int8_step(mesh8, t5):
    lm, params = t5
    return _build(lm, params, mesh8, "int8")


@pytest.fixture(scope="module")
def off_step(mesh8, t5):
    lm, params = t5
    return _build(lm, params, mesh8, "off")


def test_int8_step_trains_and_ef_sharded(mesh8, t5, int8_step, off_step):
    _, params = t5
    step_i, state_i, sh = int8_step
    step_o, state_o, _ = off_step
    batch = put_batch(_toy_batch(), mesh8)
    s1, m1 = step_i(state_i, batch)
    s0, m0 = step_o(state_o, batch)
    # loss is computed BEFORE the reduction — identical; grad_norm sees
    # only quantization noise
    assert float(m1["loss"]) == pytest.approx(float(m0["loss"]), abs=1e-6)
    g0, g1 = float(m0["grad_norm"]), float(m1["grad_norm"])
    assert abs(g0 - g1) / g0 < 5e-3
    # EF populated and laid out per the contract: worker dim over the
    # replica axes, inner dims exactly the param specs
    ef_sh = error_feedback_shardings(sh.params, mesh8)
    for (path, leaf), (_, want) in zip(
        jax.tree_util.tree_leaves_with_path(s1.ef),
        jax.tree_util.tree_leaves_with_path(ef_sh),
    ):
        assert leaf.sharding.spec == want.spec, path
    assert max(
        float(jnp.max(jnp.abs(e))) for e in jax.tree.leaves(s1.ef)
    ) > 0.0


def test_off_program_bit_identical(mesh8, t5):
    """--grad-compression off must be byte-for-byte the pre-compression
    program: the default build and an explicit off build lower to the
    SAME text (no code motion on the default path)."""
    lm, params = t5
    from distributed_llms_example_tpu.parallel.activation import (
        activation_mesh,
    )

    tx, schedule = make_optimizer(
        learning_rate=1e-3, warmup_steps=0, total_steps=1000
    )
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    batch = put_batch(_toy_batch(), mesh8)
    texts = []
    for kw in ({}, {"grad_compression": "off"}):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, donate=False, **kw
        )
        step, _ = build(state)
        with activation_mesh(mesh8):
            texts.append(step.jitted.lower(state, batch).as_text())
    assert texts[0] == texts[1]


@pytest.mark.slow
def test_int8_accum_matches_single_shot(mesh8, t5, int8_step):
    """int8 at accum=2 accumulates TILED partials and reduces once — the
    same quantizer input as accum=1, so losses and grad norms match to
    scan-reassociation noise."""
    lm, params = t5
    step1, state1, _ = int8_step
    step2, state2, _ = _build(lm, params, mesh8, "int8", accum=2)
    batch = put_batch(_toy_batch(), mesh8)
    _, m1 = step1(state1, batch)
    _, m2 = step2(state2, batch)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), abs=1e-5)
    assert float(m1["grad_norm"]) == pytest.approx(
        float(m2["grad_norm"]), rel=1e-3
    )


def test_int8_convergence_matches_fp32(mesh8, t5, int8_step, off_step):
    """Short convergence A/B on the t5-test recipe: the int8 trajectory
    tracks fp32 within tolerance (stochastic rounding is unbiased and EF
    carries what it misses)."""
    step_i, state_i, _ = int8_step
    step_o, state_o, _ = off_step
    batch = put_batch(_toy_batch(), mesh8)
    li = lo = None
    si, so = state_i, state_o
    for _ in range(8):
        si, mi = step_i(si, batch)
        so, mo = step_o(so, batch)
        li, lo = float(mi["loss"]), float(mo["loss"])
    assert lo < 6.0  # it actually trained
    assert abs(li - lo) / lo < 0.02, (li, lo)


def test_int8_census_and_comm_account(dp_mesh, t5):
    """The compiled-program verdict on the pure-replica mesh (data=8):
    the int8 program's gradient collectives ride s8, the off program's
    ride f32, and the byte accounts drop accordingly — the ir-lint
    census and the obs comm account pinned EQUAL on the same parse."""
    import math

    from distributed_llms_example_tpu.analysis.ir_lint import (
        int8_compression_missing_finding,
        parse_hlo_instructions,
        quantized_gradient_census,
        scan_hlo_text,
    )
    from distributed_llms_example_tpu.obs.gauges import collective_traffic
    from distributed_llms_example_tpu.parallel.activation import (
        activation_mesh,
    )

    lm, params = t5
    batch = put_batch(_toy_batch(), dp_mesh)
    texts = {}
    for mode in ("off", "int8"):
        step, state, _ = _build(lm, params, dp_mesh, mode)
        with activation_mesh(dp_mesh):
            texts[mode] = step.jitted.lower(state, batch).compile().as_text()
    counts = [int(math.prod(x.shape)) for x in jax.tree.leaves(params)]
    axes = dict(dp_mesh.shape)
    census = {
        m: quantized_gradient_census(parse_hlo_instructions(t), counts, axes)
        for m, t in texts.items()
    }
    # int8 program: s8 gradient collectives present; off program: none
    assert census["int8"]["s8_gradient_collectives"]
    assert not census["off"]["s8_gradient_collectives"]
    assert int8_compression_missing_finding(census["off"], "int8") is not None
    assert int8_compression_missing_finding(census["int8"], "int8") is None
    # wire estimate: ~4x fewer gradient bytes moved (f32 all-reduce ->
    # s8 all-to-all + s8 all-gather); the quantized program keeps only
    # small f32 scale traffic on the gradient account
    wire_ratio = census["off"]["gradient_wire_bytes"] / max(
        1, census["int8"]["gradient_wire_bytes"]
    )
    assert wire_ratio > 3.0, census
    s8_bytes = census["int8"]["gradient_bytes_by_dtype"].get("s8", 0)
    f32_bytes = census["int8"]["gradient_bytes_by_dtype"].get("f32", 0)
    assert s8_bytes > f32_bytes, census["int8"]
    # the obs comm account classifies the SAME bytes (shared parser +
    # candidate set): total gradient bytes equal, per parse
    for mode in ("off", "int8"):
        instrs = parse_hlo_instructions(texts[mode])
        acct = collective_traffic(instrs, counts, 8)
        assert acct["gradient_bytes"] == sum(
            census[mode]["gradient_bytes_by_dtype"].values()
        ), mode
    # and scan_hlo_text carries the census in its collective-census info
    findings = scan_hlo_text(
        texts["int8"], mesh_axes=axes, param_element_counts=counts,
        grad_compression="int8",
    )
    info = [f for f in findings if f.code == "collective-census"][0]
    assert info.context["s8_gradient_collectives"]
    assert not any(f.code == "int8-compression-missing" for f in findings)


@pytest.mark.slow
def test_checkpoint_roundtrip_and_zero_fill(tmp_path, mesh8, t5, int8_step):
    """EF rides checkpoints: an int8 state restores bit-equal (including
    the residual); a checkpoint written WITHOUT compression restores into
    an int8 run with the EF tree zero-filled (restore-less resume)."""
    from distributed_llms_example_tpu.io.checkpoint import (
        Checkpointer,
        abstract_like,
    )

    step_i, state_i, sh = int8_step
    batch = put_batch(_toy_batch(), mesh8)
    trained, _ = step_i(state_i, batch)  # non-zero EF

    ck = Checkpointer(str(tmp_path / "int8"), save_every_steps=1, keep=2,
                      async_save=False)
    assert ck.save(1, trained, force=True)
    restored, step_no = ck.restore_latest(abstract_like(trained, sh))
    assert step_no == 1
    for a, b in zip(jax.tree.leaves(trained.ef), jax.tree.leaves(restored.ef)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ck.close()

    # off-written checkpoint -> int8 resume: restore the ef-less shapes,
    # then zero-fill (the trainer's fallback path does exactly this)
    lm, params = t5
    off = create_train_state(shard_params(params, mesh8), make_optimizer(total_steps=10)[0])
    off_sh = state_shardings(off, mesh8)
    ck2 = Checkpointer(str(tmp_path / "off"), save_every_steps=1, keep=2,
                       async_save=False)
    assert ck2.save(1, off, force=True)
    with pytest.raises(Exception):
        ck2.restore_latest(abstract_like(trained, sh))
    bare = abstract_like(trained, sh).replace(ef=None)
    restored, _ = ck2.restore_latest(bare)
    filled = restored.replace(ef=jax.tree.map(
        lambda s, z: jax.device_put(z, s),
        sh.ef,
        zero_error_feedback(restored.params, worker_count(dict(mesh8.shape))),
    ))
    assert all(
        float(jnp.max(jnp.abs(e))) == 0.0 for e in jax.tree.leaves(filled.ef)
    )
    for (p, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(filled.ef),
        jax.tree_util.tree_leaves_with_path(sh.ef),
    ):
        assert a.sharding.spec == b.spec, p
    ck2.close()


def test_cli_flag_and_config():
    from distributed_llms_example_tpu.core.config import TrainConfig
    from distributed_llms_example_tpu.launch.cli import build_parser

    args = build_parser().parse_args(
        ["--grad-compression", "int8", "--train-file", "x.json"]
    )
    from distributed_llms_example_tpu.core.config import config_from_args

    cfg = config_from_args(args)
    assert cfg.grad_compression == "int8"
    assert TrainConfig().grad_compression == "off"


def test_obs_gate_gradient_bytes_ceiling(tmp_path, capsys):
    """scripts/obs_gate.py --max-gradient-bytes-per-step: fails a run
    whose startup byte account exceeds the ceiling OR that emitted no
    account at all (silently lost compression must not pass); green
    under the ceiling."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_gate",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "obs_gate.py"),
    )
    obs_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_gate)

    def write(dirname, recs):
        d = tmp_path / dirname / "obs"
        os.makedirs(d, exist_ok=True)
        with open(d / "metrics-p000.jsonl", "w") as f:
            for r in recs:
                f.write(json.dumps({"schema_version": 1, **r}) + "\n")
        return tmp_path / dirname

    gauges = {
        "event": "obs_gauges", "mesh": {"data": 8}, "flops_per_step": 1.0,
        "grad_compression": "int8",
        "comm": {
            "all-to-all": {"count": 2, "gradient_bytes": 1000,
                           "activation_bytes": 0},
            "total_bytes": 1000, "gradient_bytes": 1000,
            "activation_bytes": 0,
        },
    }
    # the wrapper always gates dispatch efficiency too — give the run a
    # healthy step_budget record so only the byte ceiling is under test
    budget = {
        "event": "step_budget", "step": 2, "window_steps": 4,
        "wall_ms": 1000.0, "data_wait_ms": 10.0, "dispatch_ms": 20.0,
        "device_busy_ms": 940.0, "sync_block_ms": 10.0,
        "host_overhead_ms": 10.0, "unattributed_ms": 10.0,
        "accounted_frac": 0.99, "additivity_ok": True,
        "dispatch_efficiency": 0.97,
        "offcadence_sync_steps": 0, "offcadence_sync_suspect": False,
    }
    good = write("good", [gauges, budget])
    assert obs_gate.main(
        [str(good), "--max-gradient-bytes-per-step", "2000"]
    ) == 0
    assert obs_gate.main(
        [str(good), "--max-gradient-bytes-per-step", "500"]
    ) == 1
    # no obs_gauges record at all: the gate must fail, not pass silently
    empty = write("empty", [{"step": 1, "loss": 1.0}, budget])
    assert obs_gate.main(
        [str(empty), "--max-gradient-bytes-per-step", "2000"]
    ) == 1
    capsys.readouterr()


def test_bench_diff_directions():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_diff",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "bench_diff.py"),
    )
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)
    assert bd.direction_of("comm_bytes_per_step.gradient_bytes_per_step") == -1
    assert bd.direction_of("grad_compression_ab.gradient_wire_bytes") == -1
    assert bd.direction_of("grad_compression") == 0
    rows = bd.compare(
        {"grad_compression_ab": {"int8_vs_off": 1.0}},
        {"grad_compression_ab": {"int8_vs_off": 0.5}},
    )
    # *_vs_* carries no direction tokens by itself; the ratio rides
    # tokens-per-sec fields which do — just pin it never crashes and the
    # byte fields gate
    rows = bd.compare(
        {"gradient_bytes_per_step": 100.0}, {"gradient_bytes_per_step": 400.0}
    )
    assert rows[0]["verdict"] == "regressed"
