"""ROUGE + Porter stemmer tests (hand-computed expectations)."""

import pytest

from distributed_llms_example_tpu.evaluation.rouge import (
    compute,
    porter_stem,
    rouge_l,
    rouge_n,
    tokenize,
)


def test_porter_classic_examples():
    # canonical examples from the Porter paper / reference implementations
    cases = {
        "caresses": "caress",
        "ponies": "poni",
        "cats": "cat",
        "feed": "feed",
        "agreed": "agre",
        "plastered": "plaster",
        "motoring": "motor",
        "sing": "sing",
        "conflated": "conflat",
        "troubled": "troubl",
        "sized": "size",
        "hopping": "hop",
        "falling": "fall",
        "hissing": "hiss",
        "failing": "fail",
        "happy": "happi",
        "relational": "relat",
        "conditional": "condit",
        "rational": "ration",
        "digitizer": "digit",
        "operator": "oper",
        "feudalism": "feudal",
        "decisiveness": "decis",
        "hopefulness": "hope",
        "formality": "formal",
        "sensitivity": "sensit",
        "triplicate": "triplic",
        "formative": "form",
        "formalize": "formal",
        "electricity": "electr",
        "electrical": "electr",
        "hopeful": "hope",
        "goodness": "good",
        "revival": "reviv",
        "allowance": "allow",
        "inference": "infer",
        "airliner": "airlin",
        "adjustable": "adjust",
        "defensible": "defens",
        "irritant": "irrit",
        "replacement": "replac",
        "adjustment": "adjust",
        "dependent": "depend",
        "adoption": "adopt",
        "communism": "commun",
        "activate": "activ",
        "angularity": "angular",
        "homologous": "homolog",
        "effective": "effect",
        "bowdlerize": "bowdler",
        "probate": "probat",
        "rate": "rate",
        "cease": "ceas",
        "controll": "control",
        "roll": "roll",
    }
    for w, want in cases.items():
        assert porter_stem(w) == want, (w, porter_stem(w), want)


def test_tokenize_stems_long_tokens_only():
    # rouge_score stems only tokens longer than 3 chars: cats→cat, the/fast kept
    assert tokenize("The cats RUNNING fast!") == ["the", "cat", "run", "fast"]
    assert tokenize("cats") == ["cat"]
    assert tokenize("cat") == ["cat"]
    assert tokenize("runs") == ["run"]


def test_rouge1_exact():
    pred = tokenize("the cat sat", use_stemmer=False)
    ref = tokenize("the cat sat on the mat", use_stemmer=False)
    # overlap 3 (the, cat, sat); p=3/3, r=3/6 → f1 = 2*.5/1.5
    assert rouge_n(pred, ref, 1) == pytest.approx(2 * 1.0 * 0.5 / 1.5)


def test_rouge2_and_l():
    pred = tokenize("a b c d", use_stemmer=False)
    ref = tokenize("a b x c d", use_stemmer=False)
    # bigrams pred: ab bc cd; ref: ab bx xc cd → overlap 2; p=2/3 r=2/4
    assert rouge_n(pred, ref, 2) == pytest.approx(2 * (2 / 3) * 0.5 / ((2 / 3) + 0.5))
    # LCS = a b c d (4); p=4/4 r=4/5
    assert rouge_l(pred, ref) == pytest.approx(2 * 1.0 * 0.8 / 1.8)


def test_identical_gets_one():
    scores = compute(["the quick brown fox"], ["the quick brown fox"])
    assert all(v == pytest.approx(1.0) for v in scores.values())


def test_disjoint_gets_zero():
    scores = compute(["aaa bbb"], ["ccc ddd"])
    assert all(v == 0.0 for v in scores.values())


def test_stemming_makes_match():
    no_stem = compute(["running jumps"], ["run jumping"], use_stemmer=False)
    stem = compute(["running jumps"], ["run jumping"], use_stemmer=True)
    assert no_stem["rouge1"] == 0.0
    assert stem["rouge1"] == pytest.approx(1.0)


def test_rouge_lsum_newlines():
    pred = "the cat sat\nthe dog ran"
    ref = "the cat sat\nthe dog ran"
    assert compute([pred], [ref])["rougeLsum"] == pytest.approx(1.0)


def test_empty_inputs():
    assert compute([], [])["rouge1"] == 0.0
    assert compute([""], ["the cat"])["rouge1"] == 0.0
