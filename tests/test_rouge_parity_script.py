"""scripts/rouge_parity.py smoke: the one-command parity runner must
exercise every stage after the download boundary (data load, Trainer
fine-tune, generation eval, JSON report) with no network and no weights."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from _dllm_env import cpu_mesh_env  # noqa: E402


@pytest.mark.slow
def test_smoke_runs_end_to_end(tmp_path):
    env = cpu_mesh_env(os.environ, n_devices=8)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "rouge_parity.py"),
         "--smoke", "--output-dir", str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = proc.stdout.strip().splitlines()[-1]
    report = json.loads(line)
    assert set(report) == {"ours", "reference", "delta"}
    assert "rougeL" in report["ours"]
    assert report["reference"] is None and report["delta"] is None


def test_acquire_model_air_gapped_message(tmp_path, monkeypatch):
    """Without egress, a hub name must fail with the pre-staging recipe,
    not an opaque network traceback."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    # force the hub client offline so the test never issues a live request
    monkeypatch.setenv("HF_HUB_OFFLINE", "1")
    import rouge_parity

    with pytest.raises(SystemExit, match="pre-stage"):
        rouge_parity.acquire_model("nonexistent/model-name-xyz")
    local = tmp_path / "ckpt"
    local.mkdir()
    assert rouge_parity.acquire_model(str(local)) == str(local)
