"""End-to-end Trainer test: tiny model, real loop, checkpoints, resume,
final export — the integration test the reference never had."""

import json
import os

import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import CheckpointConfig, MeshConfig, TrainConfig


def _records(n=32):
    rng = np.random.RandomState(0)
    out = []
    for i in range(n):
        words = " ".join(f"w{rng.randint(50)}" for _ in range(rng.randint(5, 30)))
        out.append({"dialogue": words, "summary": words.split()[0]})
    return out


@pytest.fixture(scope="module")
def tiny_cfg(tmp_path_factory):
    out = tmp_path_factory.mktemp("trainer-out")
    return TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(out),
        batch_size=8,
        num_epochs=2,
        warmup_steps=2,
        evaluation_steps=0,
        learning_rate=1e-3,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        eval_max_new_tokens=8,
        num_beams=1,
        log_every_steps=2,
        mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        checkpoint=CheckpointConfig(save_every_steps=3, keep=2, resume=True, async_save=False),
        tokenizer="byte",
    )


@pytest.mark.slow  # ~29s compile+train: the fast tier keeps the health/
# causal/recovery e2e loops; this full loop rides the slow suite
def test_trainer_end_to_end(tiny_cfg, capsys):
    from distributed_llms_example_tpu.train.trainer import Trainer

    trainer = Trainer(tiny_cfg, train_records=_records(), val_records=_records(8))
    result = trainer.train()
    assert result["steps"] == trainer.total_steps == 8  # 32/8 * 2 epochs
    assert result["final_eval"].get("epoch") == 1.0
    # final export is an HF-format checkpoint with sidecars
    model_dir = os.path.join(tiny_cfg.output_dir, "model")
    assert os.path.isfile(os.path.join(model_dir, "model.safetensors"))
    assert os.path.isfile(os.path.join(model_dir, "config.json"))
    sidecars = [f for f in os.listdir(model_dir) if f.endswith(".metadata.json")]
    assert sidecars
    # JSON-lines contract on stdout
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.startswith("{")]
    parsed = [json.loads(ln) for ln in lines]
    assert any(p.get("event") == "device_report" for p in parsed)
    assert any("loss" in p and "learning_rate" in p for p in parsed)
    assert any(p.get("event") == "eval" and "rouge1" in p for p in parsed)


@pytest.mark.slow  # rides with test_trainer_end_to_end: it resumes from
# that run's checkpoints in the module-scoped output dir
def test_trainer_resume(tiny_cfg):
    """A new Trainer over the same output dir must resume from the last
    checkpoint, not start over."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    trainer = Trainer(tiny_cfg, train_records=_records(), val_records=None)
    assert trainer.start_step == trainer.total_steps  # fully trained above
    result = trainer.train()
    assert result["steps"] == trainer.total_steps  # nothing re-run


@pytest.mark.slow  # ~40-60s of real CPU training: the fast tier keeps
# the cheap resume/CLI legs; the full e2e loops run in the slow suite
def test_trainer_profile_trace(tmp_path, capsys):
    """--profile-dir wiring: a short run must produce a jax.profiler trace
    (SURVEY.md §7 step 8) and log a profile_trace event."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    cfg = TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(tmp_path / "out"),
        batch_size=8,
        num_epochs=1,
        warmup_steps=1,
        evaluation_steps=0,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=1,
        mesh=MeshConfig(data=-1),
        checkpoint=CheckpointConfig(resume=False, async_save=False),
        tokenizer="byte",
        profile_dir=str(tmp_path / "trace"),
        profile_steps=2,
    )
    Trainer(cfg, train_records=_records()).train()
    lines = [json.loads(ln) for ln in capsys.readouterr().out.splitlines() if ln.startswith("{")]
    assert any(p.get("event") == "profile_trace" for p in lines)
    trace_files = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(cfg.profile_dir)
        for f in fs
    ]
    assert trace_files, f"no trace files under {cfg.profile_dir}"


def test_trainer_batch_too_large():
    from distributed_llms_example_tpu.train.trainer import Trainer

    cfg = TrainConfig(model_ckpt="t5-test", batch_size=64, tokenizer="byte",
                      mesh=MeshConfig(data=-1))
    with pytest.raises(ValueError, match="smaller than one"):
        Trainer(cfg, train_records=_records(8))


def test_cli_dry_run(capsys):
    from distributed_llms_example_tpu.launch.cli import main

    rc = main(["--model-ckpt", "t5-test", "--dry-run", "--mesh", "data=2,tensor=2"])
    assert rc == 0
    cfg = json.loads(capsys.readouterr().out)
    assert cfg["model_ckpt"] == "t5-test"
    assert cfg["mesh"]["tensor"] == 2


def test_cli_requires_train_file():
    from distributed_llms_example_tpu.launch.cli import main

    with pytest.raises(SystemExit, match="train-file"):
        main(["--model-ckpt", "t5-test"])


@pytest.mark.slow  # ~40-60s of real CPU training: the fast tier keeps
# the cheap resume/CLI legs; the full e2e loops run in the slow suite
def test_preemption_checkpoints_and_resumes(tmp_path):
    """SIGTERM mid-training → trainer finishes the in-flight step, saves a
    checkpoint, returns preempted=True; a fresh Trainer resumes from that
    step and completes the run.  The reference loses the whole run on
    preemption (only saves at the very end)."""
    import signal

    from distributed_llms_example_tpu.train.trainer import Trainer

    cfg = TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=2,
        warmup_steps=0,
        evaluation_steps=0,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        num_beams=1,
        log_every_steps=100,
        mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=True, async_save=False),
        tokenizer="byte",
    )
    records = _records()
    handler_before = signal.getsignal(signal.SIGTERM)

    trainer = Trainer(cfg, train_records=records)
    total = trainer.total_steps
    assert total == 8
    # deliver a real SIGTERM (to ourselves) during step 3's bookkeeping
    orig = trainer._batch_tokens
    seen = []

    def hook(batch):
        seen.append(1)
        if len(seen) == 3:
            os.kill(os.getpid(), signal.SIGTERM)
        return orig(batch)

    trainer._batch_tokens = hook
    result = trainer.train()
    assert result.get("preempted") is True
    assert result["steps"] == 3
    # handler restored to exactly what was installed before the Trainer
    assert signal.getsignal(signal.SIGTERM) is handler_before
    # no final model export on preemption
    assert not os.path.exists(os.path.join(str(tmp_path), "model", "model.safetensors"))

    resumed = Trainer(cfg, train_records=records)
    assert resumed.start_step == 3
    result2 = resumed.train()
    assert result2.get("preempted") is None
    assert result2["steps"] == total
    assert os.path.isfile(os.path.join(str(tmp_path), "model", "model.safetensors"))


@pytest.mark.slow  # two short real training runs: slow tier
def test_grad_accum_resume_on_optimizer_step_boundary(tmp_path, capsys):
    """O(1) resume under in-step accumulation: one iterator batch is one
    optimizer step regardless of grad_accum_steps, so a preemption that
    lands mid-run resumes exactly on an optimizer-step boundary — there
    is no 'mid-accumulation-window' state to lose, by construction.  The
    resumed run completes with the same step accounting as accum=1."""
    import signal

    from distributed_llms_example_tpu.train.trainer import Trainer

    cfg = TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(tmp_path),
        batch_size=8,
        grad_accum_steps=2,
        num_epochs=2,
        warmup_steps=0,
        evaluation_steps=0,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        num_beams=1,
        log_every_steps=100,
        mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=True, async_save=False),
        tokenizer="byte",
    )
    records = _records()

    trainer = Trainer(cfg, train_records=records)
    total = trainer.total_steps
    assert total == 8  # optimizer steps: 32/8 * 2 epochs, independent of accum
    # startup announces the accumulation config (microbatch = 8/2 = 4 rows)
    out = capsys.readouterr().out
    accum_events = [
        json.loads(ln) for ln in out.splitlines()
        if ln.startswith("{") and '"grad_accum"' in ln
    ]
    assert any(
        e.get("event") == "grad_accum"
        and e.get("grad_accum_steps") == 2
        and e.get("microbatch") == 4
        for e in accum_events
    )

    orig = trainer._batch_tokens
    seen = []

    def hook(batch):
        seen.append(1)
        if len(seen) == 3:
            os.kill(os.getpid(), signal.SIGTERM)
        return orig(batch)

    trainer._batch_tokens = hook
    result = trainer.train()
    assert result.get("preempted") is True
    assert result["steps"] == 3  # optimizer steps, not microbatches

    resumed = Trainer(cfg, train_records=records)
    assert resumed.start_step == 3  # O(1) resume on the optimizer-step boundary
    result2 = resumed.train()
    assert result2["steps"] == total
    assert os.path.isfile(os.path.join(str(tmp_path), "model", "model.safetensors"))

    # the per-epoch prefetch counters land in the metric stream: the
    # production consumer of Prefetcher.stats() (the per-run span-level
    # answer to whether the input pipeline is on the critical path)
    out = capsys.readouterr().out
    pf_events = [
        json.loads(ln) for ln in out.splitlines()
        if ln.startswith("{") and '"prefetch_stats"' in ln
    ]
    assert pf_events, "trainer did not emit prefetch_stats at epoch end"
    assert all(
        e["depth"] == cfg.prefetch_batches
        and e["items"] >= 1
        and e["consumer_wait_s"] >= 0.0
        for e in pf_events
    )
