"""Interleaved (virtual-stage) pipeline: schedule generator properties and
train-step equivalence against the single-device step.

The schedule is a pure-Python artifact (parallel/interleave.py) — its
validator re-derives every execution constraint from the tables alone, so
these tests focus on (a) generator properties across shapes, (b) the
executor reproducing the single-device math exactly (schedule-only
reordering), (c) the storage-order permutation round-tripping through
eval/export paths.  The reference has no pipeline parallelism (SURVEY.md
§2: model parallelism "No"); this goes past parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.parallel.interleave import (
    interleave_order,
    interleave_tree,
    make_interleaved_schedule,
    uninterleave_tree,
    validate_schedule,
)
from distributed_llms_example_tpu.parallel.pipeline import stack_blocks, unstack_blocks


@pytest.mark.parametrize(
    "S,v,M",
    [(2, 2, 4), (2, 2, 8), (4, 2, 8), (2, 4, 8), (4, 4, 16), (8, 2, 16), (3, 2, 9)],
)
def test_schedule_validates(S, v, M):
    """Generator output passes the independent table validator and stays
    within sane tick bounds (useful work is v*M ticks per device)."""
    sc = make_interleaved_schedule(S, v, M)
    validate_schedule(sc)  # idempotent re-check
    assert sc.T >= v * M
    # fill/drain overhead is bounded by the round-trip through the
    # virtual pipeline (2 * (v*S - 1) hops at one tick each)
    assert sc.T <= v * M + 2 * (v * S - 1) + S
    # the grouping order keeps queues trivially shallow
    assert sc.fq_depth <= 2 and sc.bq_depth <= 2


@pytest.mark.parametrize("S,M", [(2, 4), (4, 8)])
def test_v1_matches_plain_1f1b_tick_count(S, M):
    """virtual_stages=1 through the table machinery reproduces the plain
    1F1B schedule length T = M + 2(S-1)."""
    sc = make_interleaved_schedule(S, 1, M)
    assert sc.T == M + 2 * (S - 1)


def test_interleaving_shortens_the_schedule():
    """The point of the feature: at fixed work, interleaved ticks (each
    1/v the size) finish in less wall than v=1 ticks — T(v)/v < T(1)."""
    for S, M in [(4, 8), (8, 16)]:
        t1 = make_interleaved_schedule(S, 1, M).T
        t2 = make_interleaved_schedule(S, 2, M).T
        assert t2 / 2 < t1, f"S={S} M={M}: T(2)/2={t2 / 2} !< T(1)={t1}"


def test_interleave_order_roundtrip():
    L, S, v = 8, 2, 2
    order = interleave_order(L, S, v)
    assert sorted(order.tolist()) == list(range(L))
    # device 0 rows: chunk 0 = true layers [0,1], chunk 1 = true [4,5]
    assert order.tolist()[:4] == [0, 1, 4, 5]
    # device 1 rows: chunk 0 = true [2,3], chunk 1 = true [6,7]
    assert order.tolist()[4:] == [2, 3, 6, 7]
    x = {"w": np.arange(L * 3).reshape(L, 3)}
    rt = uninterleave_tree(interleave_tree(x, S, v), S, v)
    np.testing.assert_array_equal(rt["w"], x["w"])


def _single_device_step(cfg, module, params0, batch, tx, schedule):
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    mesh1 = build_mesh(
        MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1]
    )
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    return step(state, put_batch(batch, mesh1))


def _interleaved_step(cfg, params0, batch, tx, schedule, *, mesh, v, micro,
                      sequence_sharded=False):
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    piped = PipelinedLlama(cfg, mesh, num_microbatches=micro,
                           schedule="interleaved", virtual_stages=v)
    assert piped.pipeline_schedule == "interleaved" and piped.virtual_stages == v
    stacked = stack_blocks(params0)
    stacked["stacked_blocks"] = interleave_tree(
        stacked["stacked_blocks"], mesh.shape["stage"], v
    )
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stacked, mesh, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    return step_p(state_p, put_batch(batch, mesh, sequence_sharded=sequence_sharded))


@pytest.mark.parametrize(
    "stages,v,micro,layers",
    [(2, 2, 4, 4), (2, 2, 2, 8), (4, 2, 8, 8)],
)
def test_interleaved_train_step_equals_single_device(
    request, stages, v, micro, layers, tiny_llama8
):
    """Interleaving is a SCHEDULE-only change: loss, grad norm, and updated
    params must match the single-device step exactly — with multi-layer
    chunks (layers=8) and chunk-per-layer (layers=4) storage."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD

    if layers == 8:
        cfg, module, params0 = tiny_llama8
    else:
        cfg, module, params0 = request.getfixturevalue("tiny_llama4")
    rng = np.random.RandomState(31)
    b, src = 16, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    mask = np.ones((b, src), np.int32)
    mask[:2, -3:] = 0
    batch = {"input_ids": ids, "attention_mask": mask, "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    ref_state, ref = _single_device_step(cfg, module, params0, batch, tx, schedule)

    mesh_p = build_mesh(
        MeshConfig(stage=stages, data=8 // stages, fsdp=1, sequence=1, tensor=1)
    )
    new_state_p, got = _interleaved_step(
        cfg, params0, batch, tx, schedule, mesh=mesh_p, v=v, micro=micro
    )

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    assert float(got["target_tokens"]) == float(ref["target_tokens"])
    upd = jax.device_get(new_state_p.params)
    upd["stacked_blocks"] = uninterleave_tree(upd["stacked_blocks"], stages, v)
    upd = unstack_blocks(upd)
    ref_upd = jax.device_get(ref_state.params)
    for lyr in ("block_0", f"block_{cfg.num_hidden_layers - 1}"):
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(upd[lyr])[0]),
            np.asarray(jax.tree.leaves(ref_upd[lyr])[0]),
            atol=1e-5, rtol=1e-4,
        )
    np.testing.assert_allclose(
        np.asarray(upd["lm_head"]["kernel"]),
        np.asarray(ref_upd["lm_head"]["kernel"]),
        atol=1e-5, rtol=1e-4,
    )


def test_interleaved_composes_with_tensor(tiny_llama4):
    """stage=2 x tensor=2 x data=2 with v=2: chunk vjps still run under
    GSPMD auto-partitioning over the tensor axis."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(37)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :6] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    _, ref = _single_device_step(cfg, module, params0, batch, tx, schedule)
    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=1, tensor=2))
    _, got = _interleaved_step(
        cfg, params0, batch, tx, schedule, mesh=mesh_p, v=2, micro=2
    )
    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)


def test_interleaved_apply_uninterleaves_for_eval(tiny_llama4):
    """The gpipe eval forward (PipelinedLlama.apply) must see TRUE layer
    order: with interleaved storage the adapter un-permutes internally, so
    logits match the plain module."""
    from distributed_llms_example_tpu.models.llama import PipelinedLlama

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(41)
    ids = rng.randint(2, cfg.vocab_size, (8, 16)).astype(np.int32)
    mask = np.ones((8, 16), np.int32)
    ref = module.apply({"params": params0}, jnp.asarray(ids), jnp.asarray(mask))

    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1))
    piped = PipelinedLlama(cfg, mesh_p, num_microbatches=2,
                           schedule="interleaved", virtual_stages=2)
    stacked = stack_blocks(params0)
    stacked["stacked_blocks"] = interleave_tree(stacked["stacked_blocks"], 2, 2)
    out = piped.apply({"params": stacked}, jnp.asarray(ids), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_virtual_stages_validation():
    from distributed_llms_example_tpu.models.llama import LlamaConfig, PipelinedLlama

    mesh = build_mesh(MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1))
    cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=4, num_attention_heads=2,
    )
    with pytest.raises(ValueError, match="virtual-stages"):
        PipelinedLlama(cfg, mesh, schedule="interleaved", virtual_stages=0)
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedLlama(cfg, mesh, schedule="interleaved", virtual_stages=3)


def test_checkpoint_layout_guard(tmp_path):
    """Resuming an interleaved-layout checkpoint under a different schedule
    must hard-fail: array shapes match under any row permutation, so a
    silent restore would train a layer-permuted model."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    records = [{"dialogue": f"a b c {i}", "summary": "a b"} for i in range(16)]
    base = dict(
        model_ckpt="llama-test-4l",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        mesh=MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1),
        tokenizer="byte",
        pipeline_microbatches=2,
        pipeline_eval_rouge=False,
    )
    cfg = TrainConfig(
        **base,
        pipeline_schedule="interleaved",
        pipeline_virtual_stages=2,
        checkpoint=CheckpointConfig(save_every_steps=1, resume=True, async_save=False),
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:8])
    trainer.train()
    # same layout resumes fine
    Trainer(cfg, train_records=records, val_records=records[:8])
    # different schedule (standard layer order) must refuse the checkpoint
    cfg2 = TrainConfig(
        **base,
        pipeline_schedule="1f1b",
        checkpoint=CheckpointConfig(save_every_steps=1, resume=True, async_save=False),
    )
    with pytest.raises(ValueError, match="layout"):
        Trainer(cfg2, train_records=records, val_records=records[:8])
    # a RESIZED stage axis permutes differently under the SAME flags —
    # the layout identity is f(L, stages, v): train 8 layers interleaved
    # v=2 at stage=2, then resume v=2 at stage=4 (only `stages` differs)
    import os as _os

    dir2 = _os.path.join(str(tmp_path), "resize")
    base_s2 = dict(base, output_dir=dir2, model_ckpt="llama-test-8l")
    cfg_s2 = TrainConfig(
        **base_s2,
        pipeline_schedule="interleaved",
        pipeline_virtual_stages=2,
        checkpoint=CheckpointConfig(save_every_steps=1, resume=True, async_save=False),
    )
    Trainer(cfg_s2, train_records=records, val_records=records[:8]).train()
    base_s4 = dict(base_s2, mesh=MeshConfig(stage=4, data=2, fsdp=1, sequence=1, tensor=1))
    cfg_s4 = TrainConfig(
        **base_s4,
        pipeline_schedule="interleaved",
        pipeline_virtual_stages=2,
        checkpoint=CheckpointConfig(save_every_steps=1, resume=True, async_save=False),
    )
    with pytest.raises(ValueError, match="layout"):
        Trainer(cfg_s4, train_records=records, val_records=records[:8])
    # v=1 is the IDENTITY permutation — standard layout, so a v=1
    # interleaved run resumes plain-1f1b checkpoints (and vice versa)
    cfg_v1 = TrainConfig(
        **base_s2,
        pipeline_schedule="interleaved",
        pipeline_virtual_stages=1,
        checkpoint=CheckpointConfig(save_every_steps=1, resume=True, async_save=False),
    )
    with pytest.raises(ValueError, match="layout"):
        # the dir still holds v=2-layout checkpoints: v=1 (standard) differs
        Trainer(cfg_v1, train_records=records, val_records=records[:8])


def test_trainer_interleaved_end_to_end(tmp_path):
    """Trainer with --pipeline-schedule interleaved on stage=2 x data=4,
    v=2 (llama-test-4l): trains to finite losses, reports the pipelined
    val loss, and exports an HF checkpoint in TRUE layer order."""
    import os

    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    records = [
        {"dialogue": f"number {i} plus {i}", "summary": f"sum {2 * i}"}
        for i in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="llama-test-4l",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        mesh=MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
        pipeline_schedule="interleaved",
        pipeline_virtual_stages=2,
        pipeline_eval_rouge=False,
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:8])
    assert trainer.model.pipeline_schedule == "interleaved"
    result = trainer.train()
    assert result["steps"] == trainer.total_steps
    assert np.isfinite(result["final_eval"]["val_loss"])
    # exported checkpoint is in TRUE layer order: each per-layer block in
    # the HF artifact equals the corresponding UN-interleaved stacked row
    # of the live training state (not the raw storage row)
    from distributed_llms_example_tpu.models.registry import load_model

    reloaded = load_model(os.path.join(str(tmp_path), "model"))
    assert "stacked_blocks" not in reloaded.params
    live = jax.device_get(trainer.state.params["stacked_blocks"])
    true_order = uninterleave_tree(live, 2, 2)
    leaf = lambda tree: np.asarray(  # noqa: E731
        jax.tree.leaves(tree["self_attn"]["q_proj"])[0], np.float32
    )
    for i in range(4):
        row = jax.tree.map(lambda a: a[i], true_order)
        np.testing.assert_allclose(
            leaf(reloaded.params[f"block_{i}"]), leaf(row), atol=1e-5, rtol=1e-5
        )
