"""Per-device memory audit: BASELINE configs 4-5 must fit v5e HBM.

The analytic path (exact sharded state/grad bytes + structural remat
activation model) runs in seconds from abstract shapes — no 7B params are
ever materialized.  The compiled-path plumbing (AOT lower + XLA
memory_analysis) is exercised on the tiny config.
"""

import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.utils.memory_audit import (
    HBM_BYTES_V5E,
    audit_train_step_memory,
)


def test_flan_t5_xl_fits_8way_fsdp():
    """BASELINE config 4: flan-t5-xl, FSDP-style sharding."""
    r = audit_train_step_memory(
        "flan-t5-xl",
        mesh_config=MeshConfig(data=1, fsdp=8, sequence=1, tensor=1),
        global_batch=8,
        remat=True,
        compile=False,
    )
    assert r["params"] > 2.8e9
    assert r["fits_v5e_hbm"], f"peak {r['peak_gib']} GiB"
    assert r["fits_v5e_hbm_conservative"]
    assert r["peak_bytes"] < 0.6 * HBM_BYTES_V5E  # comfortable margin


def test_llama_2_7b_fits_8way_fsdp():
    """llama-2-7b on a single v5e-8: fp32 Adam state dominates (12
    bytes/param over 8 chips ≈ 10.1 GiB).  Fits under the optimistic
    (fused grad accumulation) bound — tight; the conservative bound needs
    the multi-host shape below, which is what BASELINE config 5 specifies."""
    r = audit_train_step_memory(
        "llama-2-7b",
        mesh_config=MeshConfig(data=1, fsdp=8, sequence=1, tensor=1),
        global_batch=8,
        remat=True,
        grad_accum_steps=8,
        compile=False,
    )
    assert r["params"] > 6.7e9
    assert r["fits_v5e_hbm"], f"peak {r['peak_gib']} GiB"


def test_llama_2_7b_multihost_fits_conservatively():
    """BASELINE config 5 is multi-host: on fsdp=16 (two v5e-8 hosts) even
    the conservative gradient-liveness bound must fit with real headroom."""
    r = audit_train_step_memory(
        "llama-2-7b",
        mesh_config=MeshConfig(data=1, fsdp=16, sequence=1, tensor=1),
        global_batch=16,
        remat=True,
        grad_accum_steps=8,
        compile=False,
    )
    assert r["fits_v5e_hbm_conservative"]
    assert r["analytic_peak_conservative_bytes"] < 0.75 * HBM_BYTES_V5E


@pytest.mark.slow  # ~14s AOT compile: slow tier (the analytic path and
# --strict bound pins stay fast)
def test_compiled_path_runs_on_tiny_config():
    """The AOT compile + memory_analysis plumbing, on a model small enough
    to compile in CI."""
    r = audit_train_step_memory(
        "t5-test",
        mesh_config=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        global_batch=8,
        src_len=64,
        tgt_len=16,
        remat=True,
        compile=True,
    )
    assert r["compiled_arguments_bytes"] > 0
    assert r["compiled_peak_bytes"] > 0
    assert r["analytic_peak_bytes"] > 0
