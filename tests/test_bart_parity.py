"""BART numerical parity vs HF PyTorch on shared random weights."""

import numpy as np
import pytest

from distributed_llms_example_tpu.evaluation.generation import make_beam_search, make_greedy_generate
from distributed_llms_example_tpu.models.bart import BartConfig, BartForConditionalGeneration
from distributed_llms_example_tpu.models.convert import convert_bart_state_dict

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def pair():
    hf_cfg = transformers.BartConfig(
        vocab_size=128,
        d_model=64,
        encoder_layers=2,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=96,
        decoder_ffn_dim=96,
        max_position_embeddings=64,
        dropout=0.0,
        attention_dropout=0.0,
        activation_dropout=0.0,
        scale_embedding=True,
        pad_token_id=1,
        bos_token_id=0,
        eos_token_id=2,
        decoder_start_token_id=2,
        forced_bos_token_id=0,
    )
    torch.manual_seed(3)
    hf_model = transformers.BartForConditionalGeneration(hf_cfg).eval()
    cfg = BartConfig(
        vocab_size=128, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=96, decoder_ffn_dim=96, max_position_embeddings=64,
        dropout_rate=0.0, scale_embedding=True, forced_bos_token_id=0,
    )
    model = BartForConditionalGeneration(cfg)
    params = convert_bart_state_dict(hf_model.state_dict())
    return hf_model, model, cfg, params


def _batch(seed=0, b=2, src=10, tgt=6, vocab=128):
    rng = np.random.RandomState(seed)
    ids = rng.randint(4, vocab, (b, src)).astype(np.int32)
    mask = np.ones((b, src), np.int32)
    mask[0, -3:] = 0
    dec = rng.randint(4, vocab, (b, tgt)).astype(np.int32)
    dec[:, 0] = 2  # decoder start
    return ids, mask, dec


def test_forward_parity(pair):
    hf_model, model, cfg, params = pair
    ids, mask, dec = _batch()
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            decoder_input_ids=torch.tensor(dec, dtype=torch.long),
        ).logits.numpy()
    got = model.apply({"params": params}, ids, mask, dec)
    np.testing.assert_allclose(np.asarray(got), ref, atol=3e-4, rtol=2e-3)


def test_greedy_parity_with_forced_bos(pair):
    hf_model, model, cfg, params = pair
    ids, mask, _ = _batch(seed=5)
    max_new = 10
    ref = hf_model.generate(
        input_ids=torch.tensor(ids, dtype=torch.long),
        attention_mask=torch.tensor(mask, dtype=torch.long),
        max_length=max_new + 1,
        num_beams=1,
        do_sample=False,
    ).numpy()[:, 1:]
    gen = make_greedy_generate(model, cfg, max_new)
    got = np.asarray(gen(params, ids, mask))
    for i in range(ids.shape[0]):
        g = got[i].tolist()
        r = ref[i].tolist()
        # compare up to first eos
        ge = g.index(2) if 2 in g else len(g)
        re_ = r.index(2) if 2 in r else len(r)
        assert g[: ge + 1][: len(r)] == r[: re_ + 1][: max_new], (i, g, r)
    assert (got[:, 0] == 0).all()  # forced bos


def test_beam_parity(pair):
    hf_model, model, cfg, params = pair
    ids, mask, _ = _batch(seed=9)
    max_new = 8
    ref = hf_model.generate(
        input_ids=torch.tensor(ids, dtype=torch.long),
        attention_mask=torch.tensor(mask, dtype=torch.long),
        max_length=max_new + 1,
        num_beams=2,
        do_sample=False,
        early_stopping=False,
        length_penalty=1.0,
    ).numpy()[:, 1:]
    gen = make_beam_search(model, cfg, max_new, num_beams=2)
    got = np.asarray(gen(params, ids, mask))
    for i in range(ids.shape[0]):
        g, r = got[i].tolist(), ref[i].tolist()
        ge = g.index(2) if 2 in g else len(g)
        re_ = r.index(2) if 2 in r else len(r)
        assert g[: ge + 1] == r[: re_ + 1], (i, g, r)
