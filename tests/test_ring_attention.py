"""Ring attention (sequence/context parallelism) correctness.

The reference has no long-context path (SURVEY.md §5: absent); these tests
hold the new ``sequence``-axis execution path to the same bar as the rest
of the framework: exact parity — forward AND gradients — against plain
softmax attention, on the 8-device CPU mesh, plus the train-step
equivalence test that catches wrong sharding end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.ops.attention import (
    dot_product_attention,
    make_causal_bias,
    mask_to_bias,
)
from distributed_llms_example_tpu.ops.mha import select_attention_impl
from distributed_llms_example_tpu.ops.ring_attention import ring_attention_sharded


@pytest.fixture(scope="module")
def sp_mesh():
    """data=2 × sequence=2 × tensor=2: ring composed with dp and tp."""
    return build_mesh(MeshConfig(data=2, fsdp=1, sequence=2, tensor=2))


@pytest.fixture(scope="module")
def deep_mesh():
    """sequence=8: every device holds 1/8 of the sequence."""
    return build_mesh(MeshConfig(data=1, fsdp=1, sequence=8, tensor=1))


def _qkv(b=4, h=4, q_len=32, kv_len=None, d=8, seed=0):
    rng = np.random.RandomState(seed)
    kv_len = kv_len or q_len
    mk = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32) * 0.5)  # noqa: E731
    return mk(b, h, q_len, d), mk(b, h, kv_len, d), mk(b, h, kv_len, d)


def _pad_bias(b, kv_len, n_pad, seed=1):
    """(b, 1, 1, kv_len) padding bias masking the last n_pad keys of half
    the batch rows (uneven masking across batch shards)."""
    mask = np.ones((b, kv_len), np.int32)
    mask[: b // 2, kv_len - n_pad :] = 0
    return mask_to_bias(jnp.asarray(mask))


def _ref(q, k, v, bias, causal):
    if causal:
        cb = make_causal_bias(q.shape[2], k.shape[2])
        bias = cb if bias is None else bias + cb
    return dot_product_attention(q, k, v, bias)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_bias", [False, True])
def test_forward_parity(sp_mesh, causal, with_bias):
    q, k, v = _qkv()
    bias = _pad_bias(q.shape[0], k.shape[2], n_pad=5) if with_bias else None
    out = ring_attention_sharded(q, k, v, bias, mesh=sp_mesh, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, bias, causal)), atol=1e-5, rtol=1e-5
    )


def test_forward_parity_deep_ring(deep_mesh):
    """8-way ring, causal: seven of eight steps are partially/fully skipped
    on some device — exercises the cond-skip and global position math."""
    q, k, v = _qkv(b=2, h=2, q_len=64)
    out = ring_attention_sharded(q, k, v, mesh=deep_mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, None, True)), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_gradient_parity(sp_mesh, causal):
    """d(sum(out·cot))/d{q,k,v} through the ring — ppermute transposes,
    checkpointed block recompute, and the cond-skip must all be exact."""
    q, k, v = _qkv(b=2, h=2, q_len=16)
    bias = _pad_bias(q.shape[0], k.shape[2], n_pad=3)
    cot = jnp.asarray(np.random.RandomState(9).randn(*q.shape).astype(np.float32))

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, bias, mesh=sp_mesh, causal=causal) * cot)

    def ref_loss(q, k, v):
        return jnp.sum(_ref(q, k, v, bias, causal) * cot)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_cross_attention_lengths(sp_mesh):
    """Decoder→encoder cross attention: q and kv lengths differ, both
    sequence-sharded, kv padding bias rotating with k/v."""
    q, k, v = _qkv(b=4, h=4, q_len=16, kv_len=32)
    bias = _pad_bias(4, 32, n_pad=7)
    out = ring_attention_sharded(q, k, v, bias, mesh=sp_mesh, causal=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, bias, False)), atol=1e-5, rtol=1e-5
    )


def test_select_impl_ring(sp_mesh):
    common = dict(
        batch=8, heads=4, head_dim=8, q_len=32, kv_len=32, use_cache=False,
        mesh=sp_mesh, backend="cpu", device_count=8,
    )
    impl, reason = select_attention_impl("auto", causal=True, **common)
    assert impl == "ring" and "sequence-parallel" in reason
    # forced
    impl, _ = select_attention_impl("ring", **common)
    assert impl == "ring"
    # decode step: never ring
    impl, _ = select_attention_impl("auto", **{**common, "use_cache": True})
    assert impl == "xla"
    # indivisible sequence → xla fallback with the blocker in the reason
    impl, reason = select_attention_impl("auto", **{**common, "q_len": 31, "kv_len": 31})
    assert impl == "xla" and "not divisible" in reason
    # causal but rectangular → fallback
    impl, reason = select_attention_impl("auto", causal=True, **{**common, "kv_len": 64})
    assert impl == "xla" and "square" in reason
    # wide bias (e.g. T5 relative-position) → fallback
    impl, reason = select_attention_impl("auto", bias_kv_only=False, **common)
    assert impl == "xla" and "K-only" in reason
    # forcing ring when it cannot run is an error, not a silent fallback
    with pytest.raises(ValueError, match="ring"):
        select_attention_impl("ring", **{**common, "q_len": 31, "kv_len": 31})


def test_mha_module_uses_ring(sp_mesh):
    """MultiHeadAttention under a sequence-parallel mesh must match its own
    no-mesh (XLA attention) output — causal, RoPE, padding bias."""
    from distributed_llms_example_tpu.ops.mha import MultiHeadAttention
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    mod = MultiHeadAttention(
        num_heads=4, head_dim=8, model_dim=32, use_bias=False, causal=True, use_rope=True
    )
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 32).astype(np.float32))
    bias = _pad_bias(4, 32, n_pad=5)
    params = mod.init(jax.random.PRNGKey(0), x)
    ref = mod.apply(params, x, bias=bias)
    with activation_mesh(sp_mesh):
        out = mod.apply(params, x, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # ~20s+ full-step compiles per model: slow tier (the
# kernel fwd/grad parity pins stay fast)
@pytest.mark.parametrize("model_name", ["bart-test", "llama-test"])
def test_train_step_equals_single_device(sp_mesh, model_name):
    """Full train step on the data×sequence×tensor mesh == single device:
    the end-to-end proof that context parallelism doesn't change numerics
    (loss, grad-norm, updated params)."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model(model_name)
    params0 = jax.device_get(lm.init_params(0))
    is_seq2seq = model_name.startswith("bart")
    rng = np.random.RandomState(3)
    b, src, tgt = 8, 16, 8
    vocab = lm.config.vocab_size
    if is_seq2seq:
        batch = {
            "input_ids": rng.randint(2, vocab, (b, src)).astype(np.int32),
            "attention_mask": np.ones((b, src), np.int32),
            "labels": rng.randint(2, vocab, (b, tgt)).astype(np.int32),
        }
        batch["attention_mask"][: b // 2, -4:] = 0  # padded sources
        batch["labels"][:2, -3:] = LABEL_PAD
    else:
        ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
        labels = ids.copy()
        labels[:, :4] = LABEL_PAD  # prompt positions are loss-masked
        batch = {
            "input_ids": ids,
            "attention_mask": np.ones((b, src), np.int32),
            "labels": labels,
        }

    tx = optax.sgd(1e-2)
    schedule = lambda step: 1e-2  # noqa: E731
    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    outs = {}
    for name, mesh in (("sp", sp_mesh), ("single", mesh1)):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh, donate=False, is_seq2seq=is_seq2seq
        )
        state = create_train_state(shard_params(params0, mesh), tx)
        sh = state_shardings(state, mesh)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        gb = put_batch(batch, mesh, sequence_sharded=mesh.shape.get("sequence", 1) > 1)
        new_state, metrics = step(state, gb)
        outs[name] = (
            jax.device_get(new_state.params),
            float(metrics["loss"]),
            float(metrics["grad_norm"]),
        )
    p_sp, loss_sp, gn_sp = outs["sp"]
    p_1, loss_1, gn_1 = outs["single"]
    assert loss_sp == pytest.approx(loss_1, rel=1e-5)
    assert gn_sp == pytest.approx(gn_1, rel=1e-4)
    for a, b_ in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5, rtol=2e-5)


def test_non_divisible_lengths_fall_back(sp_mesh):
    """Batch lengths that don't divide the sequence axis must still train:
    the caller passes sequence_sharded=False (as Trainer does after its
    bucket-width check) and the model falls back to XLA attention for the
    non-divisible shapes instead of crashing in device_put/dispatch."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("llama-test")
    rng = np.random.RandomState(7)
    b, src = 8, 15  # 15 % sequence(2) != 0
    ids = rng.randint(2, lm.config.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :3] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}

    tx = optax.sgd(1e-2)
    build = make_train_step(
        lm.module, lm.config, tx, lambda s: 1e-2, sp_mesh,
        donate=False, is_seq2seq=False, sequence_sharded=False,
    )
    state = create_train_state(shard_params(jax.device_get(lm.init_params(0)), sp_mesh), tx)
    sh = state_shardings(state, sp_mesh)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    _, metrics = step(state, put_batch(batch, sp_mesh, sequence_sharded=False))
    assert np.isfinite(float(metrics["loss"]))


def test_forced_ring_tolerates_meshless_traces():
    """attention_impl='ring' must not explode during module init (no mesh
    context) — real config errors raise under a mesh (above) and at
    Trainer startup (mesh/stage validation in train/trainer.py)."""
    impl, reason = select_attention_impl(
        "ring", batch=1, heads=4, head_dim=8, q_len=8, kv_len=8,
        use_cache=False, mesh=None, backend="cpu", device_count=8,
    )
    assert impl == "xla" and "ring requested" in reason
