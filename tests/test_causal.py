"""Causal-LM path: greedy parity vs HF, dataset masking, end-to-end training."""

import numpy as np
import pytest

from distributed_llms_example_tpu.data.dataset import CausalLMDataset
from distributed_llms_example_tpu.data.tokenizer import ByteTokenizer
from distributed_llms_example_tpu.evaluation.generation import make_causal_greedy
from distributed_llms_example_tpu.models.convert import convert_llama_state_dict
from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def test_causal_greedy_parity_uniform_prompt():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
        attention_dropout=0.0, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(21)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
    )
    model = LlamaForCausalLM(cfg)
    params = convert_llama_state_dict(hf.state_dict())

    rng = np.random.RandomState(2)
    ids = rng.randint(3, 128, (2, 8)).astype(np.int32)
    mask = np.ones((2, 8), np.int32)
    max_new = 8
    ref = hf.generate(
        input_ids=torch.tensor(ids, dtype=torch.long),
        attention_mask=torch.tensor(mask, dtype=torch.long),
        max_new_tokens=max_new,
        do_sample=False,
    ).numpy()[:, 8:]
    gen = make_causal_greedy(model, cfg, max_new)
    got = np.asarray(gen(params, ids, mask))
    for i in range(2):
        g, r = got[i].tolist(), ref[i].tolist()
        ge = g.index(2) if 2 in g else len(g)
        re_ = r.index(2) if 2 in r else len(r)
        assert g[: ge + 1] == r[: re_ + 1], (i, g, r)


@pytest.mark.parametrize("seed,length_penalty", [(33, 1.0), (34, 1.0), (35, 2.0)])
def test_causal_beam_parity_vs_hf(seed, length_penalty):
    """Token parity with HF ``generate(num_beams=2)`` on shared random
    weights — the reference's live eval contract for causal models
    (reference train-accelerator.py:247).  A small vocab (32) puts EOS in
    the top-2K regularly, exercising the banking/is_done paths, and the
    length_penalty=2 case makes finished-vs-live selection order matter."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from distributed_llms_example_tpu.evaluation.generation import make_causal_beam_search

    hf_cfg = transformers.LlamaConfig(
        vocab_size=32, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
        attention_dropout=0.0, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=32, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
    )
    model = LlamaForCausalLM(cfg)
    params = convert_llama_state_dict(hf.state_dict())

    rng = np.random.RandomState(seed)
    prompt_len, max_new = 8, 12
    ids = rng.randint(3, 32, (4, prompt_len)).astype(np.int32)
    mask = np.ones((4, prompt_len), np.int32)
    ref = hf.generate(
        input_ids=torch.tensor(ids, dtype=torch.long),
        attention_mask=torch.tensor(mask, dtype=torch.long),
        max_new_tokens=max_new,
        num_beams=2,
        do_sample=False,
        length_penalty=length_penalty,
        early_stopping=False,
    ).numpy()[:, prompt_len:]
    gen = make_causal_beam_search(model, cfg, max_new, num_beams=2, length_penalty=length_penalty)
    got = np.asarray(gen(params, ids, mask))

    def content(seq):
        """Generated content, HF-convention-neutral: HF stores beam
        hypotheses WITHOUT the terminating eos (output shows pads there),
        ours include it — compare tokens before eos/padding."""
        toks = seq.tolist()
        if 2 in toks:
            toks = toks[: toks.index(2)]
        while toks and toks[-1] == 0:
            toks.pop()
        return toks

    def norm_score(prompt, toks):
        """Length-normalized logprob of a hypothesis under the HF model."""
        full = list(prompt) + toks
        with torch.no_grad():
            lp = torch.log_softmax(hf(torch.tensor([full], dtype=torch.long)).logits[0].float(), -1)
        s = sum(lp[len(prompt) - 1 + i, toks[i]].item() for i in range(len(toks)))
        return s / (len(full) ** length_penalty)

    for i in range(ids.shape[0]):
        ours, hfs = content(got[i]), content(ref[i])
        if ours == hfs:
            continue
        # Beam search is a heuristic search, and HF's vectorized scorer can
        # drop paths near score ties; divergence is acceptable ONLY when our
        # hypothesis is at least as good under HF's own model + length
        # normalization (observed: penalty=2.0 cases where ours wins).
        assert norm_score(ids[i], ours) >= norm_score(ids[i], hfs) - 1e-6, (
            i, got[i].tolist(), ref[i].tolist()
        )


@pytest.mark.slow  # ~12s generation compile: slow tier (beam-parity
# legs keep padding coverage fast)
def test_causal_greedy_right_padded_rows_match_unpadded():
    """A batch of right-padded prompts must generate exactly what each row
    generates alone without padding (true-sequence RoPE positions)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
        attention_dropout=0.0, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(33)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
    )
    model = LlamaForCausalLM(cfg)
    params = convert_llama_state_dict(hf.state_dict())
    gen = make_causal_greedy(model, cfg, 6)

    rng = np.random.RandomState(7)
    row_a = rng.randint(3, 128, 9).tolist()
    row_b = rng.randint(3, 128, 5).tolist()
    width = 9
    ids = np.zeros((2, width), np.int32)
    mask = np.zeros((2, width), np.int32)
    ids[0, :9], mask[0, :9] = row_a, 1
    ids[1, :5], mask[1, :5] = row_b, 1
    batched = np.asarray(gen(params, ids, mask))
    for r, row in enumerate((row_a, row_b)):
        solo = np.asarray(
            gen(params, np.asarray([row], np.int32), np.ones((1, len(row)), np.int32))
        )[0]
        np.testing.assert_array_equal(batched[r], solo, err_msg=f"row {r}")


def test_causal_evaluator_beams(dp_mesh):
    """Evaluator with num_beams=2 exercises the beam path end-to-end for
    decoder-only models (prompt-continuation ROUGE)."""
    from distributed_llms_example_tpu.evaluation.evaluate import Evaluator
    from distributed_llms_example_tpu.models.registry import load_model

    lm = load_model("llama-test")
    tok = ByteTokenizer()
    records = [{"dialogue": f"prompt text {i}", "summary": f"target {i}"} for i in range(8)]
    ds = CausalLMDataset(records, tok, max_length=64)
    ev = Evaluator(
        lm.module, lm.config, tok, dp_mesh, num_beams=2, max_new_tokens=8, is_seq2seq=False
    )
    params = lm.init_params(0)
    scores = ev.run(params, ds, global_batch=8, bucket_multiple=16, max_source_length=32)
    assert set(scores) >= {"rouge1", "rouge2", "rougeL"}


def test_causal_dataset_masks_prompt():
    tok = ByteTokenizer()
    ds = CausalLMDataset(
        [{"dialogue": "abcd", "summary": "xy"}], tok, max_length=32, max_target_length=8
    )
    ex = ds[0]
    assert len(ex.input_ids) == len(ex.labels)
    n_prompt = len(ex.prompt_ids)
    assert all(v == -100 for v in ex.labels[:n_prompt])
    assert ex.labels[n_prompt:] == ex.target_ids
    assert ex.target_ids[-1] == tok.eos_id


@pytest.mark.slow  # ~10s training loop: slow tier (the trainer e2e
# suites keep loop coverage fast)
def test_causal_training_end_to_end(tmp_path):
    """llama-test trains and evals through the full Trainer."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, MeshConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(0)
    recs = [
        {"dialogue": " ".join(f"w{rng.randint(30)}" for _ in range(8)), "summary": "w1 w2"}
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="llama-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=0,
        evaluation_steps=0,
        learning_rate=1e-3,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        eval_max_new_tokens=8,
        num_beams=1,
        mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
    )
    tr = Trainer(cfg, train_records=recs, val_records=recs[:8])
    result = tr.train()
    assert result["steps"] == 2
    assert "rouge1" in result["final_eval"]
