"""Pod-agreement static analysis: SPMD divergence lint + collective census.

Acceptance pins (ISSUE 16): Layer 1 (host AST taint) flags every seeded
historical-bug fixture under tests/fixtures/divergence/ and reports ZERO
findings on the production tree; Layer 2 (HLO census) extracts a stable
ordered collective signature from compiled programs, checks worker-group
factorization compatibility within and across paired programs, and the
compiled fsdp=8 t5-test train step's collective ordering is pinned as a
golden.  Plus the end-to-end ``--strict --divergence`` CLI run over the
t5-test and llama-test configs (satellite: fast tier-1 gate).
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_llms_example_tpu.analysis import divergence, ir_lint
from distributed_llms_example_tpu.analysis.ir_lint import CollectiveSig

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "divergence")


def _codes(findings):
    return sorted({f.code for f in findings})


def _fixture(name):
    path = os.path.join(FIXTURES, name)
    return divergence.analyze_file(path, rel=f"fixtures/{name}")


# ---------------------------------------------------------------------------
# Layer 1 — the seeded historical bug shapes (satellite 1)
# ---------------------------------------------------------------------------

def test_flags_one_rank_exception_walkback():
    """INCIDENT shape 1: one rank's restore raises, only THAT rank walks
    back to an older checkpoint — its collective sequence diverges."""
    findings = _fixture("bad_exception_walkback.py")
    assert any(f.code == "rank-divergent-collective" for f in findings)
    assert all(f.severity == "error" for f in findings)
    assert any("except" in f.message for f in findings)


def test_flags_p0_only_unbroadcast_verdict():
    """INCIDENT shape 2: p0 verifies, the verdict never rides a
    broadcast — implicit-flow taint must catch the pod-uniform-looking
    ``if not ok:`` that follows."""
    findings = _fixture("bad_p0_verdict.py")
    assert any(f.code == "rank-divergent-collective" for f in findings)


def test_flags_rank_varying_retry_count():
    """INCIDENT shape 3: the retry ladder's trip count comes from a LOCAL
    directory listing; ranks with fewer candidates run fewer collectives."""
    findings = _fixture("bad_retry_count.py")
    assert any(f.code == "rank-divergent-loop" for f in findings)


def test_flags_rank_divergent_early_exit():
    """A p0-gated early return splits the pod: survivors run the
    collectives below, the exiting ranks never arrive."""
    findings = _fixture("bad_early_exit.py")
    assert any(f.code == "rank-divergent-early-exit" for f in findings)


def test_good_agreed_fixture_is_clean():
    """The SAME recovery shapes routed through the agreement sanitizers
    (the patterns io/checkpoint.py ships) must come out clean — a finding
    here is a false positive, as bad as a miss on a bad_* file."""
    assert _fixture("good_agreed.py") == []


# ---------------------------------------------------------------------------
# Layer 1 — semantics on inline sources
# ---------------------------------------------------------------------------

BAD_INLINE = """\
import jax

def f(ckpt, state, step):
    if jax.process_index() == 0:
        ckpt.save(state, step)
"""


def test_inline_divergent_sink_flagged():
    findings = divergence.analyze_source(BAD_INLINE, "inline.py")
    assert _codes(findings) == ["rank-divergent-collective"]
    f = findings[0]
    assert f.context["sink"] == "save"
    assert f.context["function"] == "f"
    assert f.context["divergent_line"] == 4


def test_pragma_waives_finding():
    waived = BAD_INLINE.replace(
        "== 0:", "== 0:  # pod-agreed: gathers already ran; LOCAL write only",
    )
    assert divergence.analyze_source(waived, "inline.py") == []
    # ...and the pragma works on the sink line too
    waived = BAD_INLINE.replace(
        "ckpt.save(state, step)",
        "ckpt.save(state, step)  # pod-agreed: p0-local sidecar",
    )
    assert divergence.analyze_source(waived, "inline.py") == []


def test_sanitizer_untaints():
    src = """\
import jax

def f(ckpt, state, step):
    ok = jax.process_index() == 0
    if ckpt._agreed_ok(ok):
        ckpt.save(state, step)
"""
    assert divergence.analyze_source(src, "inline.py") == []


def test_taint_flows_through_assignment():
    src = """\
import os

def f(ckpt, state, d):
    names = os.listdir(d)
    latest = sorted(names)[-1]
    if latest:
        ckpt.restore_before(state, int(latest))
"""
    findings = divergence.analyze_source(src, "inline.py")
    assert _codes(findings) == ["rank-divergent-collective"]


def test_pod_uniform_condition_is_clean():
    """process_count() is the SAME on every rank — conditioning on it is
    rule 13's (lexical) business, not a divergence error."""
    src = """\
import jax

def f(ckpt, state, step):
    if jax.process_count() == 1:
        ckpt.save(state, step)
"""
    assert divergence.analyze_source(src, "inline.py") == []


def test_registries_are_spec_owned():
    """The source/sanitizer/sink registries are the analysis contract:
    every entry carries a rationale, and the names the codebase's
    agreement story is built on are present."""
    for registry in (divergence.SOURCES, divergence.SANITIZERS, divergence.SINKS):
        assert registry and all(
            isinstance(v, str) and v for v in registry.values()
        )
    assert "process_index" in divergence.SOURCES
    assert {"_agreed_step", "_agreed_ok", "_agreed_count",
            "sync_global_devices", "broadcast_one_to_all"} <= set(
        divergence.SANITIZERS)
    assert {"save", "restore_latest", "train_step", "put_batch"} <= set(
        divergence.SINKS)


def test_production_tree_is_clean():
    """The whole package under the divergence pass: zero findings — every
    rank-gated site either routes through a sanitizer or carries a
    ``# pod-agreed:`` pragma naming its agreement mechanism."""
    findings, files_scanned = divergence.analyze_tree()
    assert files_scanned >= 70
    assert findings == [], [f.message for f in findings]


# ---------------------------------------------------------------------------
# Layer 2 — collective signatures on synthetic HLO
# ---------------------------------------------------------------------------

SYNTH_HLO = """\
ENTRY main {
  %p0 = f32[8,128]{1,0} parameter(0)
  %ag = f32[64,128]{1,0} all-gather(%p0), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
  %ar-start = f32[64,128]{1,0} all-reduce-start(%ag), channel_id=2, replica_groups={{0,4},{1,5},{2,6},{3,7}}
  %ar-done = f32[64,128]{1,0} all-reduce-done(%ar-start)
  %rs = f32[8,128]{1,0} reduce-scatter(%ar-done), channel_id=3, replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
}
"""


def test_collective_signature_order_and_fields():
    sig = ir_lint.collective_signature(SYNTH_HLO)
    assert [s.op for s in sig] == ["all-gather", "all-reduce", "reduce-scatter"]
    # -done halves are dropped: each collective counts ONCE, at issue
    assert len(sig) == 3
    assert sig[0].channel_id == 1
    assert sig[0].groups == "{0,1,2,3},{4,5,6,7}"
    # operand bytes: p0 is 8*128 f32
    assert sig[0].operand_bytes == 8 * 128 * 4


def test_partition_compatibility():
    fsdp = ((0, 1, 2, 3), (4, 5, 6, 7))     # replica axis slices
    data = ((0, 4), (1, 5), (2, 6), (3, 7))  # the orthogonal axis
    straddle = ((0, 1, 2), (3, 4, 5), (6, 7))  # hand-rolled, uneven
    assert ir_lint.partitions_compatible(fsdp, data)
    assert ir_lint.partitions_compatible(fsdp, fsdp)
    assert not ir_lint.partitions_compatible(fsdp, straddle)
    # canonical text is enumeration-order independent
    assert ir_lint.canonical_partition_text(((4, 6), (0, 2), (5, 7), (1, 3))) \
        == ir_lint.canonical_partition_text(((0, 2), (1, 3), (4, 6), (5, 7)))
    # iota/world groups partition trivially
    assert ir_lint.parse_group_partition("[1,8]<=[8]") is None
    assert ir_lint.parse_group_partition("") is None


def test_signature_order_finding():
    a = (CollectiveSig("all-reduce", "", 1, 64),)
    b = (CollectiveSig("all-gather", "", 1, 64),)
    assert ir_lint.signature_order_finding("p", a, a) is None
    f = ir_lint.signature_order_finding("p", a, b)
    assert f is not None and f.code == "nondeterministic-collective-order"
    assert f.severity == "error" and f.context["position"] == 0


def test_census_cross_program_mismatch():
    train = (CollectiveSig(
        "all-reduce", "{{0,1,2,3},{4,5,6,7}}", 1, 64),)
    rogue = (CollectiveSig(
        "all-to-all", "{{0,1,2},{3,4,5},{6,7}}", 2, 64),)
    findings = ir_lint.census_findings(
        {"train": train, "rogue": rogue}, pairs=[("train", "rogue")],
    )
    # one info census row per program...
    infos = [f for f in findings if f.code == "collective-signature"]
    assert len(infos) == 2
    assert infos[0].context["ops"] == {"all-reduce": 1}
    # ...and the straddling pair is an error
    errors = [f for f in findings if f.code == "collective-group-mismatch"]
    assert len(errors) == 1 and errors[0].severity == "error"
    # compatible pairs are quiet
    data = (CollectiveSig("all-reduce", "{{0,4},{1,5},{2,6},{3,7}}", 1, 64),)
    findings = ir_lint.census_findings(
        {"train": train, "decode": data}, pairs=[("train", "decode")],
    )
    assert [f for f in findings if f.severity == "error"] == []


def test_census_within_program_incompatible():
    prog = (
        CollectiveSig("all-reduce", "{{0,1,2,3},{4,5,6,7}}", 1, 64),
        CollectiveSig("all-to-all", "{{0,1,2},{3,4,5},{6,7}}", 2, 64),
    )
    findings = ir_lint.census_findings({"p": prog})
    assert any(f.code == "collective-group-incompatible" for f in findings)


# ---------------------------------------------------------------------------
# Layer 2 — the golden fsdp=8 train-step ordering (satellite 4)
# ---------------------------------------------------------------------------

# Run-length-encoded op-kind sequence of the compiled t5-test train step
# on an fsdp=8 mesh (batch 8, src 64, tgt 16, f32 optimizer): the param
# all-gathers, the backward gradient all-reduces, and the trailing
# all-to-alls of the reduce-scatter lowering, in scheduler order.  A
# toolchain bump that legitimately reorders collectives shows up as ONE
# reviewed diff here — regenerate with
# ``ir_lint.collective_signature(...)`` over a fresh compile.
GOLDEN_FSDP8_TRAIN_RLE = [
    ("all-gather", 15),
    ("all-reduce", 1),
    ("all-gather", 20),
    ("all-reduce", 67),
    ("all-gather", 2),
    ("all-reduce", 51),
    ("all-to-all", 6),
]


def _rle(ops):
    out = []
    for op in ops:
        if out and out[-1][0] == op:
            out[-1] = (op, out[-1][1] + 1)
        else:
            out.append((op, 1))
    return out


def test_golden_fsdp8_train_step_collective_ordering():
    """The census's anchor program: compile the fsdp=8 t5-test train step
    and pin its ordered collective signature.  Any drift in WHICH
    collectives run, their ORDER, or their worker groups is a reviewed
    change, not silent."""
    from distributed_llms_example_tpu.core.config import MeshConfig

    collect = {}
    ir_lint.lint_train_step(
        "t5-test", mesh_config=MeshConfig(fsdp=8),
        global_batch=8, src_len=64, tgt_len=16,
        collect=collect, program="train_step",
    )
    sig = ir_lint.collective_signature(collect["train_step"])
    assert _rle([s.op for s in sig]) == GOLDEN_FSDP8_TRAIN_RLE
    # every explicit worker grouping is the world group or the fsdp-axis
    # iota — ONE factorization, trivially self-compatible
    assert sorted({s.groups for s in sig}) == [
        "[1,8]<=[8]", "{0,1,2,3,4,5,6,7}",
    ]
    census = ir_lint.census_findings({"train_step": sig})
    assert [f for f in census if f.severity == "error"] == []


# ---------------------------------------------------------------------------
# The end-to-end strict gate (satellite 5) + CLI coverage contract
# ---------------------------------------------------------------------------

STRICT_CONFIGS = [
    ("t5-test", "data=2,fsdp=2,tensor=2"),
    ("llama-test", "fsdp=4"),
]


@pytest.mark.parametrize("model,mesh", STRICT_CONFIGS)
def test_strict_divergence_gate_subprocess(model, mesh):
    """The CI gate the ISSUE ships: ``lint --strict --divergence`` over
    the test configs must exit 0.  ``--no-ir`` keeps it fast and
    device-independent; the skipped programs appear as NAMED coverage
    entries (the silent-gap fix), asserted below."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_llms_example_tpu.analysis.lint",
         "--model", model, "--mesh", mesh, "--batch", "8",
         "--src-len", "64", "--tgt-len", "16",
         "--strict", "--divergence", "--no-ir", "--json"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    events = [json.loads(ln) for ln in proc.stdout.splitlines()
              if ln.startswith("{")]
    coverage = {e["pass"]: e for e in events
                if e.get("event") == "lint_coverage"}
    # the divergence pass RAN over the tree...
    assert coverage["divergence"]["files_scanned"] >= 70
    # ...and the skipped IR programs are named, with reasons — no silent
    # coverage gaps
    skipped = coverage["ir"]["programs_skipped"]
    assert skipped and all(e["reason"] == "--no-ir" for e in skipped)
    assert any(e["program"].startswith("train_step") for e in skipped)
    summary = [e for e in events if e.get("event") == "lint_summary"][-1]
    assert summary["programs_skipped"] == len(skipped)
    assert summary["programs_scanned"] == 0
