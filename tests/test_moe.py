"""Mixture-of-experts (expert parallelism) correctness.

Covers the dense-dispatch routing math, the Switch load-balance loss, the
Mixtral-class LLaMA integration, and — same bar as every other axis —
sharded-vs-single-device train-step equivalence with experts split over
the ``tensor`` axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.ops.moe import MoEMLP


def _x(b=2, s=8, d=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(b, s, d).astype(np.float32) * 0.5)


def test_single_expert_equals_dense_mlp():
    """E=1 top-1 with ample capacity routes every token to the only expert
    with gate 1.0 — the layer must equal a plain SwiGLU with its weights."""
    x = _x()
    moe = MoEMLP(num_experts=1, intermediate_size=32, top_k=1, capacity_factor=4.0)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    out = moe.apply({"params": params}, x)

    wg, wu, wd = (params[k][0] for k in ("gate_proj", "up_proj", "down_proj"))
    flat = x.reshape(-1, x.shape[-1])
    ref = (jax.nn.silu(flat @ wg) * (flat @ wu)) @ wd
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref.reshape(x.shape)), atol=1e-5, rtol=1e-5)


def test_top2_gates_sum_to_one_no_drops():
    """With ample capacity every token lands in exactly its top-2 experts
    and the (renormalized) combine mass per token is 1."""
    x = _x(b=2, s=16, d=8, seed=3)
    moe = MoEMLP(num_experts=4, intermediate_size=16, top_k=2, capacity_factor=8.0)
    params = moe.init(jax.random.PRNGKey(1), x)["params"]

    # reproduce the routing host-side from the router weights
    logits = x.reshape(-1, 8) @ params["router"]["kernel"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top2, _ = jax.lax.top_k(probs, 2)
    out = moe.apply({"params": params}, x)
    assert np.isfinite(np.asarray(out)).all()
    # gates renormalized: scaling the top-2 winners can't change the output mix sum
    np.testing.assert_allclose(
        np.asarray(jnp.sum(top2 / jnp.sum(top2, -1, keepdims=True), -1)), 1.0, rtol=1e-6
    )


def test_capacity_drops_tokens():
    """capacity_factor too small → overflow tokens produce zero output
    (the residual connection carries them in a real block)."""
    d = 8
    x = _x(b=1, s=32, d=d, seed=5)
    moe = MoEMLP(num_experts=2, intermediate_size=16, top_k=1, capacity_factor=0.25)
    params = moe.init(jax.random.PRNGKey(2), x)["params"]
    out = np.asarray(moe.apply({"params": params}, x)).reshape(-1, d)
    dropped = np.sum(np.all(out == 0.0, axis=-1))
    # capacity = ceil-ish of 32/2 * 0.25 = 4 per expert → ≥ 32 - 8 dropped
    assert dropped >= 32 - 2 * 4


def test_aux_loss_uniform_routing_is_one():
    """The Switch load-balance loss is exactly 1.0 under uniform routing
    (zero router weights → uniform probs, ties broken deterministically)."""
    x = _x(b=2, s=8, d=16, seed=7)
    moe = MoEMLP(num_experts=4, intermediate_size=16, top_k=1, capacity_factor=4.0)
    params = moe.init(jax.random.PRNGKey(3), x)["params"]
    params = jax.tree.map(np.asarray, params)
    params["router"]["kernel"] = np.zeros_like(params["router"]["kernel"])
    _, mutated = moe.apply({"params": params}, x, mutable=["losses"])
    aux = float(jax.tree.leaves(mutated["losses"])[0])
    # uniform probs: P_e = 1/E exactly; top-1 ties all resolve to expert 0,
    # so frac = one_hot(0) and aux = E * (1 * 1/E) = 1.0
    assert aux == pytest.approx(1.0, rel=1e-5)


@pytest.mark.slow  # ~11s mixtral compile: slow tier (routing pins
# stay fast)
def test_mixtral_forward_and_aux_plumbing(mesh8):
    """Mixtral-class model: logits well-formed; moe_aux_weight>0 routes the
    sown loss into the train-step objective (loss changes with the weight)."""
    import dataclasses

    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("mixtral-test")
    rng = np.random.RandomState(0)
    b, s = 8, 16
    ids = rng.randint(2, lm.config.vocab_size, (b, s)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, s), np.int32), "labels": labels}

    params0 = jax.device_get(lm.init_params(0))
    tx = optax.sgd(1e-2)
    losses = {}
    for weight in (0.0, 0.5):
        cfg = dataclasses.replace(lm.config, moe_aux_weight=weight)
        build = make_train_step(
            lm.module, cfg, tx, lambda _: 1e-2, mesh8, donate=False, is_seq2seq=False
        )
        state = create_train_state(shard_params(params0, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, sp: jax.device_put(x, sp), state, sh)
        step, _ = build(state)
        _, metrics = step(state, put_batch(batch, mesh8))
        losses[weight] = float(metrics["loss"])
    assert np.isfinite(losses[0.0]) and np.isfinite(losses[0.5])
    # aux ≈ 1 at near-uniform init → weighted loss is visibly larger
    assert losses[0.5] > losses[0.0] + 0.2


@pytest.mark.slow  # ~18s two-topology compile: slow tier (forward/aux
# plumbing and routing pins stay fast)
def test_moe_sharded_step_equals_single_device(mesh8):
    """Expert-parallel train step == single device on TWO topologies:
    the general mesh8 (expert=1: experts replicated, megatron splits over
    tensor) and the decoupled EP×TP mesh (expert=2,tensor=2: experts over
    their own axis COMPOSED with column/row splits) — loss, grad-norm,
    updated params all match."""
    import optax

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("mixtral-test")
    params0 = jax.device_get(lm.init_params(0))
    rng = np.random.RandomState(9)
    b, s = 8, 16
    ids = rng.randint(2, lm.config.vocab_size, (b, s)).astype(np.int32)
    labels = ids.copy()
    labels[:2, :6] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, s), np.int32), "labels": labels}

    tx = optax.sgd(1e-2)
    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    mesh_ep = build_mesh(MeshConfig(data=2, fsdp=1, expert=2, sequence=1, tensor=2))
    outs = {}
    for name, mesh in (("sharded", mesh8), ("ep_tp", mesh_ep), ("single", mesh1)):
        build = make_train_step(
            lm.module, lm.config, tx, lambda _: 1e-2, mesh, donate=False, is_seq2seq=False
        )
        state = create_train_state(shard_params(params0, mesh), tx)
        sh = state_shardings(state, mesh)
        state = jax.tree.map(lambda x, sp: jax.device_put(x, sp), state, sh)
        step, _ = build(state)
        new_state, metrics = step(state, put_batch(batch, mesh))
        outs[name] = (
            jax.device_get(new_state.params),
            float(metrics["loss"]),
            float(metrics["grad_norm"]),
        )
    p_1, loss_1, gn_1 = outs["single"]
    for name in ("sharded", "ep_tp"):
        p_sh, loss_sh, gn_sh = outs[name]
        assert loss_sh == pytest.approx(loss_1, rel=1e-5), name
        assert gn_sh == pytest.approx(gn_1, rel=1e-4), name
        for a, b_ in zip(jax.tree.leaves(p_sh), jax.tree.leaves(p_1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5, rtol=2e-5)
    # EP × TP really compose: gate_proj (E=4, d, ff) shards E=4 over
    # expert=2 AND ff over tensor=2 — (2, d, ff/2) per device
    gate = shard_params(params0, mesh_ep)["block_0"]["mlp"]["gate_proj"]
    E, d, ff = params0["block_0"]["mlp"]["gate_proj"].shape
    assert {s.data.shape for s in gate.addressable_shards} == {(E // 2, d, ff // 2)}

def test_grouped_routing_matches_ungrouped():
    """With ample capacity, routing decisions are per-token, so splitting
    tokens into groups (the linear-memory GShard form) must not change the
    output — including when the group size doesn't divide the token count
    (padding tokens claim no capacity)."""
    x = _x(b=2, s=12, d=8, seed=13)  # 24 tokens; group 7 → pad 4
    kw = dict(num_experts=4, intermediate_size=16, top_k=2, capacity_factor=8.0)
    whole = MoEMLP(group_size=4096, **kw)
    params = whole.init(jax.random.PRNGKey(4), x)["params"]
    ref = whole.apply({"params": params}, x)
    grouped = MoEMLP(group_size=7, **kw)
    out = grouped.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_mixtral_hf_parity():
    """Forward parity vs HF MixtralForCausalLM on shared random weights:
    the converter's expert stacking (w1→gate, w3→up, w2→down, transposed)
    and our top-2 renormalized routing must reproduce HF logits (HF routes
    without capacity limits, so ample capacity_factor removes drops)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import dataclasses

    from distributed_llms_example_tpu.models.convert import convert_llama_state_dict
    from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_dropout=0.0, pad_token_id=0, bos_token_id=1, eos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(23)
    hf_model = transformers.MixtralForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        # <=0 = no-drop on every path, the converted-checkpoint setting
        num_experts=4, num_experts_per_tok=2, moe_capacity_factor=-1.0,
    )
    model = LlamaForCausalLM(cfg)
    params = convert_llama_state_dict(hf_model.state_dict())

    rng = np.random.RandomState(1)
    ids = rng.randint(3, 128, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[1, -4:] = 0
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).logits.numpy()
    out = np.asarray(model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask)))
    # padded rows differ (HF masks differently past pads); compare valid positions
    np.testing.assert_allclose(out[0], ref[0], atol=2e-4, rtol=2e-3)
    np.testing.assert_allclose(out[1, :8], ref[1, :8], atol=2e-4, rtol=2e-3)


def test_local_mixtral_checkpoint_loads(tmp_path):
    """A local HF Mixtral checkpoint dir (config.json model_type=mixtral +
    weights) resolves through the registry: config parsed (experts, top-k,
    aux coef), weights converted, one forward step runs."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import json

    from distributed_llms_example_tpu.models.registry import load_model

    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2, router_aux_loss_coef=0.05,
        max_position_embeddings=64, attention_dropout=0.0,
    )
    torch.manual_seed(3)
    hf_model = transformers.MixtralForCausalLM(hf_cfg)
    ckpt = tmp_path / "mixtral"
    ckpt.mkdir()
    torch.save(hf_model.state_dict(), ckpt / "pytorch_model.bin")
    (ckpt / "config.json").write_text(json.dumps({**hf_cfg.to_dict(), "model_type": "mixtral"}))

    lm = load_model(str(ckpt))
    assert lm.family == "llama" and not lm.is_seq2seq
    assert lm.config.num_experts == 4
    assert lm.config.num_experts_per_tok == 2
    assert lm.config.moe_aux_weight == pytest.approx(0.05)
    assert lm.params is not None and "router" in lm.params["block_0"]["mlp"]
    ids = np.ones((1, 8), np.int32)
    logits = lm.module.apply({"params": lm.params}, ids, np.ones_like(ids))
    assert np.isfinite(np.asarray(logits)).all()


def test_sharded_safetensors_checkpoint_loads(tmp_path):
    """Real 7B+/mixtral checkpoints ship as model-0000N-of-000NN.safetensors
    shards plus an index json — the local loader must reassemble them."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import json

    from safetensors.numpy import save_file

    from distributed_llms_example_tpu.models.registry import load_model

    hf_cfg = transformers.MixtralConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=2, num_experts_per_tok=2,
        max_position_embeddings=64, attention_dropout=0.0,
    )
    torch.manual_seed(5)
    sd = {k: v.numpy() for k, v in transformers.MixtralForCausalLM(hf_cfg).state_dict().items()}
    ckpt = tmp_path / "sharded"
    ckpt.mkdir()
    keys = sorted(sd)
    half = len(keys) // 2
    shards = {
        "model-00001-of-00002.safetensors": {k: sd[k] for k in keys[:half]},
        "model-00002-of-00002.safetensors": {k: sd[k] for k in keys[half:]},
    }
    weight_map = {k: shard for shard, kv in shards.items() for k in kv}
    for shard, kv in shards.items():
        save_file(kv, ckpt / shard)
    (ckpt / "model.safetensors.index.json").write_text(json.dumps({"weight_map": weight_map}))
    (ckpt / "config.json").write_text(json.dumps({**hf_cfg.to_dict(), "model_type": "mixtral"}))

    lm = load_model(str(ckpt))
    assert lm.params is not None
    # converted checkpoints default to no-drop routing (HF parity everywhere)
    assert lm.config.moe_capacity_factor <= 0
    ids = np.ones((1, 8), np.int32)
    logits = lm.module.apply({"params": lm.params}, ids, np.ones_like(ids))
    assert np.isfinite(np.asarray(logits)).all()
