"""Serving subsystem: decode flash kernel parity, sharded KV-cache lint,
prefill/decode split, continuous batching determinism, prefill-in-decode IR
smell."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.ops.attention import NEG_INF, dot_product_attention
from distributed_llms_example_tpu.ops.flash_attention import (
    flash_decode,
    flash_decode_supported,
)
from distributed_llms_example_tpu.ops.mha import decode_step_bias, select_decode_impl


# ------------------------------------------------------ kernel unit parity


def _dense_decode_ref(q, k, v, bias, offsets, scale=None):
    """Masked dot_product_attention with the kernel's per-row length mask."""
    L = k.shape[2]
    Q = q.shape[2]
    k_pos = jnp.arange(L)[None, None, None, :]
    q_pos = offsets[:, None, None, None] + jnp.arange(Q)[None, None, :, None]
    step = jnp.where(k_pos <= q_pos, 0.0, NEG_INF)
    return dot_product_attention(q, k, v, step if bias is None else bias + step, scale=scale)


@pytest.mark.parametrize("q_len", [1, 4])
def test_flash_decode_matches_dense(q_len):
    rng = np.random.RandomState(0)
    B, H, L, d = 3, 4, 64, 16
    q = jnp.asarray(rng.randn(B, H, q_len, d).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, L, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, L, d).astype(np.float32))
    bias = jnp.asarray(
        np.where(rng.rand(B, 1, 1, L) > 0.2, 0.0, NEG_INF).astype(np.float32)
    )
    # ragged per-row offsets: fresh slot (0), mid-decode, cache-full
    offsets = jnp.array([0, 17, L - q_len], jnp.int32)
    out = flash_decode(q, k, v, bias, offsets=offsets)
    ref = _dense_decode_ref(q, k, v, bias, offsets)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_flash_decode_stale_cache_unreachable():
    """Slot-reuse contract: whatever sits beyond a row's offset (a previous
    occupant's K/V) must not influence the output."""
    rng = np.random.RandomState(1)
    B, H, L, d = 2, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, 1, d).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, L, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, L, d).astype(np.float32))
    offsets = jnp.array([3, 9], jnp.int32)
    out = flash_decode(q, k, v, offsets=offsets)
    # poison everything beyond each row's offset with huge garbage
    k_pos = jnp.arange(L)[None, None, :, None]
    beyond = k_pos > offsets[:, None, None, None]
    out_poisoned = flash_decode(
        q,
        jnp.where(beyond, 1e6, k),
        jnp.where(beyond, -1e6, v),
        offsets=offsets,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_poisoned))


def test_flash_decode_supported_gating():
    assert flash_decode_supported(1, 128, 64)
    assert flash_decode_supported(8, 64, 16)
    assert not flash_decode_supported(9, 128, 64)  # q block too tall
    assert not flash_decode_supported(1, 12, 64)  # 12 not 8-tileable
    assert not flash_decode_supported(1, 128, 12)  # head_dim not lane-aligned


def test_select_decode_impl_pure():
    kw = dict(batch=8, heads=8, head_dim=64, q_len=1, kv_len=128, mesh=None,
              backend="tpu", device_count=1)
    assert select_decode_impl("auto", **kw)[0] == "flash_decode"
    assert select_decode_impl("xla", **kw) == ("xla", "forced")
    assert select_decode_impl("ring", **kw)[0] == "xla"
    impl, reason = select_decode_impl("auto", **{**kw, "backend": "cpu"})
    assert impl == "xla" and "cpu" in reason
    # forced flash wins on any backend when the shape tiles
    assert select_decode_impl("flash", **{**kw, "backend": "cpu"})[0] == "flash_decode"
    # untileable cache falls back even when forced
    assert select_decode_impl("flash", **{**kw, "kv_len": 12})[0] == "xla"


def test_decode_step_bias_per_row():
    offsets = jnp.array([0, 5], jnp.int32)
    bias = decode_step_bias(offsets, 1, 8)
    assert bias.shape == (2, 1, 1, 8)
    row0 = np.asarray(bias)[0, 0, 0]
    row1 = np.asarray(bias)[1, 0, 0]
    assert (row0[:1] == 0).all() and (row0[1:] < -1e8).all()
    assert (row1[:6] == 0).all() and (row1[6:] < -1e8).all()


def test_cached_decode_keeps_probs_dropout():
    """A cached decode step that WANTS attention-probs dropout (MC-dropout
    eval: deterministic=False + a dropout rng) must keep applying it —
    the decode kernel has no mask stream, so the dispatch falls back to
    the XLA path instead of silently going deterministic."""
    from distributed_llms_example_tpu.ops.mha import MultiHeadAttention

    mha = MultiHeadAttention(
        num_heads=2, head_dim=8, model_dim=16, causal=True,
        attention_impl="flash", probs_dropout_rate=0.5,
    )
    rng = np.random.RandomState(0)
    x_full = jnp.asarray(rng.randn(2, 16, 16).astype(np.float32))
    variables = mha.init(jax.random.PRNGKey(0), x_full, use_cache=True)
    x = x_full[:, :1]
    kw = dict(use_cache=True, mutable=["cache"])
    det, _ = mha.apply(variables, x, deterministic=True, **kw)
    drop1, _ = mha.apply(
        variables, x, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(1)}, **kw,
    )
    drop2, _ = mha.apply(
        variables, x, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(2)}, **kw,
    )
    assert not np.allclose(np.asarray(det), np.asarray(drop1))
    assert not np.allclose(np.asarray(drop1), np.asarray(drop2))


# ------------------------------------------ kernel parity through decoding


def _with_impl(lm, impl):
    cfg = dataclasses.replace(lm.config, attention_impl=impl)
    return type(lm.module)(cfg), cfg


def test_seq2seq_decode_kernel_parity_greedy_and_beam():
    """Forced-flash cached decode (the Pallas decode kernel, interpret mode
    on CPU) is token-identical to the XLA reference on greedy AND beam
    paths — the bit-parity acceptance gate."""
    from distributed_llms_example_tpu.evaluation.generation import (
        make_beam_search,
        make_greedy_generate,
    )

    lm = load_model("t5-test")
    params = lm.init_params(0)
    rng = np.random.RandomState(2)
    ids = rng.randint(2, 200, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), np.int32)
    mask[1, -5:] = 0
    for factory, kw in (
        (make_greedy_generate, {}),
        (make_beam_search, {"num_beams": 2}),
    ):
        outs = {}
        for impl in ("xla", "flash"):
            mod, cfg = _with_impl(lm, impl)
            outs[impl] = np.asarray(factory(mod, cfg, 16, **kw)(params, ids, mask))
        np.testing.assert_array_equal(outs["xla"], outs["flash"])


def test_causal_decode_kernel_parity_ragged_prompts():
    """LLaMA cached decode through the kernel: ragged (right-padded)
    prompts exercise per-row causal offsets; greedy + beam vs XLA."""
    from distributed_llms_example_tpu.evaluation.generation import (
        make_causal_beam_search,
        make_causal_greedy,
    )

    lm = load_model("llama-test")
    params = lm.init_params(0)
    rng = np.random.RandomState(3)
    ids = rng.randint(3, 120, (3, 8)).astype(np.int32)
    mask = np.ones((3, 8), np.int32)
    mask[1, -3:] = 0
    mask[2, -1:] = 0
    for factory, kw in (
        (make_causal_greedy, {}),
        (make_causal_beam_search, {"num_beams": 2}),
    ):
        outs = {}
        for impl in ("xla", "flash"):
            mod, cfg = _with_impl(lm, impl)
            outs[impl] = np.asarray(factory(mod, cfg, 8, **kw)(params, ids, mask))
        np.testing.assert_array_equal(outs["xla"], outs["flash"])


# --------------------------------------------------- cache sharding lint


def test_cache_rules_lint_green_on_abstract_cache():
    from distributed_llms_example_tpu.analysis.spec_lint import lint_cache_sharding
    from distributed_llms_example_tpu.evaluation.generation import abstract_cache

    axes = {"data": 2, "fsdp": 2, "tensor": 2}
    for name, seq2seq in (("t5-test", True), ("bart-test", True), ("llama-test", False)):
        lm = load_model(name, load_weights=False)
        a_params = jax.eval_shape(lambda lm=lm: lm.init_params(0))
        cache = abstract_cache(
            lm.module, a_params, batch=8, max_new_tokens=16, src_len=32,
            is_seq2seq=seq2seq,
        )
        findings = lint_cache_sharding(cache, axes)
        errors = [f for f in findings if f.severity == "error"]
        assert not errors, errors


def test_cache_rules_lint_catches_unmatched_leaf():
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.analysis.spec_lint import lint_cache_sharding
    from distributed_llms_example_tpu.evaluation.generation import abstract_cache
    from distributed_llms_example_tpu.parallel.sharding import ShardingRules

    lm = load_model("t5-test", load_weights=False)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    cache = abstract_cache(lm.module, a_params, batch=8, max_new_tokens=16, src_len=32)
    # a typo'd rule set: cached_value leaves match nothing → they decode
    # fully replicated
    bad = ShardingRules(rules=[
        (r"cached_key$", P(("data", "fsdp"), "tensor", None, None)),
        (r"cache_index$", P()),
    ])
    findings = lint_cache_sharding(cache, {"data": 2, "fsdp": 2, "tensor": 2}, rules=bad)
    assert any(f.code == "unmatched-cache-leaf" for f in findings)


def test_cache_resolves_on_mesh8(mesh8):
    """The cache rule set drives real NamedSharding resolution for the
    serving state — cached K/V shards batch over data×fsdp and heads over
    tensor on the 8-device mesh."""
    from distributed_llms_example_tpu.evaluation.generation import abstract_cache
    from distributed_llms_example_tpu.parallel.sharding import (
        cache_rules,
        resolve_shardings,
    )

    lm = load_model("t5-test", load_weights=False)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    cache = abstract_cache(lm.module, a_params, batch=8, max_new_tokens=16, src_len=32)
    sh = resolve_shardings(cache, mesh8, cache_rules())
    leaves = jax.tree_util.tree_leaves_with_path(sh)
    kv = [
        (path, s) for path, s in leaves
        if "cached_key" in str(path) or "cached_value" in str(path)
    ]
    assert kv
    for path, s in kv:
        spec = s.spec
        assert spec[0] == ("data", "fsdp", "expert"), (path, spec)
        assert spec[1] == "tensor", (path, spec)


def test_aot_decode_program_carries_cache_rules_sharding(mesh8):
    """The cache spec lint's claim, proven on the COMPILED program: the
    AOT-compiled prefill emits its cache carry (the decode step's input)
    sharded exactly per CACHE_RULES — batch rows over (data, fsdp), heads
    over tensor — not whatever GSPMD would guess for an unconstrained
    zeros-init."""
    import jax.tree_util as jtu

    from distributed_llms_example_tpu.evaluation.generation import Seq2SeqGenerator
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    lm = load_model("t5-test", load_weights=False)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    gen = Seq2SeqGenerator(lm.module, lm.config, 16, num_beams=1)
    ids = jax.ShapeDtypeStruct((8, 32), jnp.int32)
    with activation_mesh(mesh8):
        compiled = jax.jit(gen.prefill).lower(a_params, ids, ids).compile()
    kv = [
        (jtu.keystr(path), s.spec)
        for path, s in jtu.tree_leaves_with_path(compiled.output_shardings["cache"])
        if "cached_key" in jtu.keystr(path) or "cached_value" in jtu.keystr(path)
    ]
    assert kv
    for path, spec in kv:
        batch_axes = spec[0] if len(spec) > 0 else None
        batch_axes = batch_axes if isinstance(batch_axes, tuple) else (batch_axes,)
        assert {"data", "fsdp"} <= set(batch_axes), (path, spec)
        assert len(spec) > 1 and spec[1] == "tensor", (path, spec)


# ---------------------------------------- AOT decode step: spec + IR lint


@pytest.mark.parametrize("name", ["t5-test", "llama-test"])
def test_decode_step_compiles_green(name):
    """The acceptance gate: the compiled per-token decode step carries no
    encoder recompute and no per-step cross-KV re-projection
    (prefill_in_decode_smell green), on the multi-axis mesh."""
    from distributed_llms_example_tpu.analysis.ir_lint import lint_decode_step
    from distributed_llms_example_tpu.core.config import MeshConfig

    findings = lint_decode_step(
        name,
        mesh_config=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        slots=8, src_len=32, max_new_tokens=16,
    )
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, errors


def test_prefill_in_decode_smell_fixture():
    """Pure-predicate check on seeded HLO: a decode-legit cross-attention
    score dot stays quiet; a re-projected cross-KV-sized dot errors."""
    from distributed_llms_example_tpu.analysis.ir_lint import (
        parse_hlo_instructions,
        prefill_in_decode_smell,
        scan_hlo_text,
    )

    enc_len, B, H, dh = 128, 8, 4, 64
    ok_text = f"""
  %scores = f32[{B},{H},1,{enc_len}]{{3,2,1,0}} dot(%q, %k)
  %ctx = f32[{B},{H},1,{dh}]{{3,2,1,0}} dot(%p, %v)
"""
    bad_text = ok_text + f"""
  %reproj = f32[{B},{enc_len},{H * dh}]{{2,1,0}} dot(%enc, %w)
"""
    contract = dict(enc_len=enc_len, batch=B, heads=H, q_len=1)
    assert prefill_in_decode_smell(parse_hlo_instructions(ok_text), **contract) is None
    finding = prefill_in_decode_smell(parse_hlo_instructions(bad_text), **contract)
    assert finding is not None and finding.code == "prefill-in-decode"
    assert "reproj" in str(finding.context["instructions"])
    # wired through scan_hlo_text via decode_contract
    codes = [f.code for f in scan_hlo_text(bad_text, mesh_axes={}, decode_contract=contract)]
    assert "prefill-in-decode" in codes


# ----------------------------------------------- continuous batching


def _requests(rng, n, lo=3, hi=20, vocab=200):
    return [list(rng.randint(4, vocab, rng.randint(lo, hi))) for _ in range(n)]


def test_engine_matches_static_batching_seq2seq(mesh8, capsys):
    """Determinism acceptance: an admit/evict schedule over reused slots
    produces EXACTLY the tokens static batching produces, per request —
    with per-request budgets (the continuous-batching lever) exercised.
    The per-request lifecycle spans (ISSUE 9) ride the same run: one
    serve_request event per request with the queue-wait/prefill/decode
    decomposition, and serve_summary's TTFT split accounts for them."""
    import json as _json

    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
        static_batch_generate,
        trim_eos,
    )

    lm = load_model("bart-test")
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    params = shard_params(lm.init_params(0), mesh8)
    rng = np.random.RandomState(7)
    reqs = _requests(rng, 10)
    L, W = 12, 32
    budgets = [int(b) for b in rng.randint(4, L + 1, len(reqs))]
    eng = ServingEngine(
        lm.module, lm.config, mesh8,
        ServeConfig(max_slots=4, prefill_batch=4, max_new_tokens=L,
                    max_source_length=W, log_every_steps=0),
        is_seq2seq=True,
    )
    capsys.readouterr()
    outs = eng.generate(params, reqs, max_new=budgets)
    assert eng.last_stats is not None and eng.last_stats.decode_steps > 0
    assert eng.last_stats.ttft_s and len(eng.last_stats.ttft_s) == len(reqs)
    # slot reuse genuinely happened: 10 requests through 4 slots
    assert eng.last_stats.sequences > eng.S
    # per-request lifecycle spans: one serve_request per request, each
    # decomposed (queue-wait + prefill <= ttft; decode + evict step), and
    # the summary's TTFT split covers every finished request
    events = [
        _json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    spans = [e for e in events if e.get("event") == "serve_request"]
    assert sorted(e["request"] for e in spans) == list(range(len(reqs)))
    for e in spans:
        assert {"slot", "queue_wait_ms", "prefill_ms", "ttft_ms",
                "decode_ms", "tokens", "t_admit_s", "t_done_s",
                "finished_at_step"} <= set(e)
        assert e["tokens"] == len(outs[e["request"]])
        # TTFT covers at least the queue-wait and this chunk's prefill
        assert e["ttft_ms"] >= e["queue_wait_ms"] + e["prefill_ms"] - 0.5
    # late-admitted requests (slot reuse) genuinely waited in queue
    assert max(e["queue_wait_ms"] for e in spans) > 0
    summary = next(e for e in events if e.get("event") == "serve_summary")
    assert {"ttft_queue_p50_ms", "ttft_queue_p95_ms", "ttft_prefill_p50_ms",
            "ttft_prefill_p95_ms", "ttft_queue_share",
            "ttft_prefill_share"} <= set(summary)
    assert 0.0 <= summary["ttft_queue_share"] <= 1.0
    assert len(eng.last_stats.queue_wait_s) == len(reqs)
    # goodput (ISSUE 11 satellite): useful tokens/sec rides the summary;
    # with no SLO configured every finished token is useful and the SLO
    # fields stay absent (0 = off, not "everything attained")
    assert summary["goodput_tokens_per_sec"] > 0
    assert summary["goodput_tokens_per_sec_chip"] > 0
    assert "slo_attainment" not in summary and "ttft_slo_ms" not in summary
    assert eng.last_stats.goodput["goodput_tokens_per_sec"] == summary[
        "goodput_tokens_per_sec"
    ]
    ref = static_batch_generate(
        lm.module, lm.config, mesh8, params, reqs, max_new_tokens=L, width=W, batch=4
    )
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want, budget in zip(outs, ref, budgets):
        g = trim_eos(got, eos, pad)
        w = trim_eos(want, eos, pad)[: len(g)]
        # engine stops at the per-request budget; static decodes to L —
        # the engine's tokens must be the static prefix (eos-trimmed)
        assert g == w, (g, w)
        assert len(g) <= budget


def test_compute_goodput_slo_arithmetic():
    """The goodput fields pinned on hand numbers: useful tokens are the
    tokens of requests whose TTFT met the SLO; attainment counts finished
    requests only; no SLO → every finished token is useful and the SLO
    fields are absent."""
    from distributed_llms_example_tpu.serving.engine import compute_goodput

    ttft = [0.1, 0.4, None, 0.2]  # request 2 never finished
    tokens = [10, 20, 99, 30]
    g = compute_goodput(
        ttft, tokens, wall_s=2.0, ttft_slo_ms=250.0, n_chips=2
    )
    # met: requests 0 and 3 → 40 useful tokens over 2 s
    assert g["goodput_tokens_per_sec"] == 20.0
    assert g["goodput_tokens_per_sec_chip"] == 10.0
    assert g["ttft_slo_ms"] == 250.0
    assert g["slo_attainment"] == pytest.approx(2 / 3, abs=1e-4)
    # SLO off: all finished tokens are useful, no attainment claim
    g0 = compute_goodput(ttft, tokens, wall_s=2.0, ttft_slo_ms=0.0, n_chips=2)
    assert g0["goodput_tokens_per_sec"] == 30.0
    assert "slo_attainment" not in g0
    # nothing finished at all: zero goodput, zero attainment
    g_none = compute_goodput(
        [None, None], [5, 5], wall_s=1.0, ttft_slo_ms=100.0, n_chips=1
    )
    assert g_none["goodput_tokens_per_sec"] == 0.0
    assert g_none["slo_attainment"] == 0.0


def test_engine_matches_static_batching_causal(mesh8):
    from distributed_llms_example_tpu.evaluation.generation import CausalGenerator
    from distributed_llms_example_tpu.parallel.activation import activation_mesh
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
        trim_eos,
    )

    lm = load_model("llama-test")
    params = shard_params(lm.init_params(0), mesh8)
    rng = np.random.RandomState(9)
    reqs = _requests(rng, 6, lo=3, hi=14, vocab=120)
    W, L = 16, 8
    eng = ServingEngine(
        lm.module, lm.config, mesh8,
        ServeConfig(max_slots=4, prefill_batch=4, max_new_tokens=L,
                    max_source_length=W, log_every_steps=0),
        is_seq2seq=False,
    )
    outs = eng.generate(params, reqs)
    gen = CausalGenerator(lm.module, lm.config, L, num_beams=1)
    run = jax.jit(gen.run)
    ref = []
    for lo in range(0, len(reqs), 2):
        chunk = reqs[lo : lo + 2]
        ids = np.full((2, W), lm.config.pad_token_id, np.int32)
        mask = np.zeros((2, W), np.int32)
        for r, req in enumerate(chunk):
            ids[r, : len(req)] = req
            mask[r, : len(req)] = 1
        with activation_mesh(None):
            got = np.asarray(run(params, jnp.asarray(ids), jnp.asarray(mask)))
        ref.extend(got[r].tolist() for r in range(len(chunk)))
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want in zip(outs, ref):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)


def test_engine_validates_composition_and_shards():
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.serving.engine import ServeConfig, ServingEngine

    lm = load_model("t5-test", load_weights=False)
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    with pytest.raises(ValueError, match="batch shards"):
        ServingEngine(
            lm.module, lm.config, mesh,
            ServeConfig(max_slots=4, prefill_batch=2), is_seq2seq=True,
        )
    seq_mesh = build_mesh(MeshConfig(data=4, fsdp=1, sequence=2, tensor=1))
    with pytest.raises(ValueError, match="sequence"):
        ServingEngine(
            lm.module, lm.config, seq_mesh,
            ServeConfig(max_slots=4, prefill_batch=4), is_seq2seq=True,
        )


def test_decode_composition_rows():
    from distributed_llms_example_tpu.analysis.composition import failing_combos

    assert not failing_combos(flags=("decode", "seq2seq"), mesh_axes={"data": 4, "fsdp": 2})
    assert not failing_combos(flags=("decode", "causal"), mesh_axes={"fsdp": 4, "tensor": 2})
    bad = failing_combos(flags=("decode", "seq2seq"), mesh_axes={"stage": 2, "data": 4})
    assert [row.id for row in bad] == ["decode-pipelined"]
    bad = failing_combos(flags=("decode", "causal"), mesh_axes={"sequence": 2, "data": 4})
    assert [row.id for row in bad] == ["decode-sequence"]


# -------------------------------------------------------------- serve CLI


@pytest.mark.slow
def test_serve_cli_end_to_end(tmp_path):
    import json

    from distributed_llms_example_tpu.launch.cli import serve_main

    prompts = tmp_path / "prompts.json"
    prompts.write_text(json.dumps([
        {"dialogue": f"prompt number {i} with some words", "summary": "x"}
        for i in range(5)
    ]))
    out = tmp_path / "out.jsonl"
    rc = serve_main([
        "--model-ckpt", "t5-test",
        "--prompts-file", str(prompts),
        "--output-file", str(out),
        "--max-slots", "8", "--prefill-batch", "8",
        "--max-new-tokens", "8", "--max-source-length", "32",
        "--compute-dtype", "float32", "--log-every-steps", "0",
    ])
    assert rc == 0
    recs = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(recs) == 5
    assert all({"prompt", "output", "tokens"} <= set(r) for r in recs)
