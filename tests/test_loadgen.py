"""Open-loop load observability (ISSUE 17): seeded arrival schedules,
the virtual-clock QPS sweep, knee detection, the closed-loop-vs-open-loop
disagreement pin, the report's sweep section + strict gates, and the
serve_request arrival/queue-delay schema growth.

The deterministic tier runs on a session-shaped fake whose clock is a
``VirtualClock`` shared with the driver — schedule, queueing, and
verdicts replay bit-for-bit with no wall clock anywhere.  The slow tier
drives a real tiny engine and pins the determinism contract (open-loop
tokens == the closed-loop oracle's) plus genuine queueing collapse."""

import importlib.util
import json
import os

import numpy as np
import pytest

from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.report import (
    build_report,
    render_markdown,
)
from distributed_llms_example_tpu.serving.loadgen import (
    EngineTarget,
    LoadgenConfig,
    RouterTarget,
    VirtualClock,
    arrival_schedule,
    detect_knee,
    drive_open_loop,
    queue_growing,
    summarize_point,
    sweep_qps,
)


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


# ---------------------------------------------------------------------------
# pure logic: config validation, arrival schedules, knee detection
# ---------------------------------------------------------------------------


def test_loadgen_config_validation():
    with pytest.raises(ValueError, match="process"):
        LoadgenConfig(process="uniform")
    with pytest.raises(ValueError, match="burst_size"):
        LoadgenConfig(burst_size=0)
    with pytest.raises(ValueError, match="ramp_start_frac"):
        LoadgenConfig(ramp_start_frac=0.0)
    with pytest.raises(ValueError, match="at least one"):
        LoadgenConfig(qps_grid=())
    with pytest.raises(ValueError, match="positive"):
        LoadgenConfig(qps_grid=(1.0, -2.0))
    with pytest.raises(ValueError, match="ascend"):
        LoadgenConfig(qps_grid=(4.0, 2.0))


@pytest.mark.parametrize("process", ["poisson", "bursty", "ramp"])
def test_arrival_schedule_deterministic(process):
    """The determinism acceptance pin: same seed + config → bit-identical
    float64 schedule; a different seed or rate → a different one."""
    a = arrival_schedule(process, qps=4.0, n=64, seed=3)
    b = arrival_schedule(process, qps=4.0, n=64, seed=3)
    assert a.dtype == np.float64 and len(a) == 64
    assert (a == b).all()
    assert (np.diff(a) >= 0).all() and (a > 0).all()
    assert not (a == arrival_schedule(process, qps=4.0, n=64, seed=4)).all()
    assert not (a == arrival_schedule(process, qps=8.0, n=64, seed=3)).all()
    # the average rate is the offered rate (law of large numbers at n=64:
    # a loose band is enough to catch a rate-off-by-k bug)
    assert 2.0 < 64 / a[-1] < 8.0


def test_arrival_schedule_shapes_and_errors():
    # bursty: burst_size arrivals share each instant
    s = arrival_schedule("bursty", qps=8.0, n=12, seed=0, burst_size=4)
    assert len(set(s[:4])) == 1 and len(set(s[4:8])) == 1
    assert s[0] < s[4] < s[8]
    # ramp: the early arrivals come at a slower instantaneous rate, so
    # the first half spans more time than the second half
    r = arrival_schedule("ramp", qps=8.0, n=200, seed=0, ramp_start_frac=0.2)
    assert (r[99] - r[0]) > (r[199] - r[100])
    with pytest.raises(ValueError, match="n must be"):
        arrival_schedule("poisson", qps=1.0, n=0, seed=0)
    with pytest.raises(ValueError, match="qps must be"):
        arrival_schedule("poisson", qps=0.0, n=4, seed=0)
    with pytest.raises(ValueError, match="process"):
        arrival_schedule("uniform", qps=1.0, n=4, seed=0)


def _point(offered, *, achieved=None, growing=False, shed=0):
    return {
        "offered_qps": offered,
        "achieved_qps": offered if achieved is None else achieved,
        "queue_growing": growing,
        "shed": shed,
    }


def test_detect_knee_pinned_curves():
    """The knee is the FIRST saturated offered rate, by any of the three
    saturation signals, in grid order."""
    # throughput stops tracking the offer
    assert detect_knee([
        _point(1.0), _point(2.0), _point(4.0, achieved=3.0), _point(8.0, achieved=3.1),
    ]) == 4.0
    # unbounded queue growth fires first
    assert detect_knee([
        _point(1.0), _point(2.0, growing=True), _point(4.0, achieved=1.0),
    ]) == 2.0
    # shed requests saturate even when achieved tracks
    assert detect_knee([_point(1.0), _point(2.0, shed=3)]) == 2.0
    # every point tracks: the grid never reached saturation
    assert detect_knee([_point(1.0), _point(2.0), _point(4.0)]) is None
    # track_tol moves the tracking bar
    curve = [_point(2.0, achieved=1.9)]
    assert detect_knee(curve, track_tol=0.9) is None
    assert detect_knee(curve, track_tol=0.99) == 2.0


def test_queue_growing_verdicts():
    def row(arrival, ttft, finished=True):
        return {"arrival_s": arrival, "ttft_s": ttft, "finished": finished,
                "shed": False}

    # stationary waits: not growing
    flat = [row(i * 1.0, 0.05) for i in range(8)]
    assert not queue_growing(flat, 8.0)
    # the last quarter waits 10x the first: growing
    ramp = [row(i * 1.0, 0.01 if i < 6 else 0.5) for i in range(8)]
    assert queue_growing(ramp, 8.0)
    # an unfinished tail IS unbounded growth
    tail = flat[:-1] + [row(7.0, None, finished=False)]
    assert queue_growing(tail, 8.0)
    # under 4 rows there's no head/tail to compare
    assert not queue_growing(flat[:3], 3.0)


def test_summarize_point_missing_measurement_is_none():
    """A fully-collapsed point (nothing finished) must report its TTFT
    percentiles as None — 0.0 would PASS a --max-p99-ttft-ms gate."""
    rows = [
        {"arrival_s": float(i), "queue_delay_s": None, "ttft_s": None,
         "finished": False, "shed": False}
        for i in range(4)
    ]
    p = summarize_point(rows, offered_qps=2.0, ttft_slo_ms=100.0, wall_s=10.0)
    assert p["completed"] == 0 and p["unfinished"] == 4
    assert p["ttft_p99_ms"] is None and p["ttft_p50_ms"] is None
    assert p["slo_attainment"] == 0.0 and p["goodput_qps"] == 0.0
    assert p["queue_growing"] is True


def test_summarize_point_slo_over_offered_denominator():
    """SLO attainment is judged over every OFFERED request: unfinished
    and shed requests are misses, never dropped from the denominator."""
    rows = [
        {"arrival_s": 0.0, "queue_delay_s": 0.0, "ttft_s": 0.01,
         "finished": True, "shed": False},
        {"arrival_s": 1.0, "queue_delay_s": 0.0, "ttft_s": 5.0,
         "finished": True, "shed": False},  # finished but missed the SLO
        {"arrival_s": 2.0, "queue_delay_s": 0.0, "ttft_s": None,
         "finished": False, "shed": True},  # shed = a miss
        {"arrival_s": 3.0, "queue_delay_s": None, "ttft_s": None,
         "finished": False, "shed": False},  # unfinished = a miss
    ]
    p = summarize_point(rows, offered_qps=1.0, ttft_slo_ms=100.0, wall_s=4.0)
    assert p["offered"] == 4 and p["completed"] == 2 and p["shed"] == 1
    assert p["slo_attainment"] == 0.25
    assert p["goodput_qps"] == 0.25


# ---------------------------------------------------------------------------
# the deterministic fake tier: a session-shaped fake over a VirtualClock
# ---------------------------------------------------------------------------


class ClockedFakeSession:
    """ServeSession surface with a deterministic service model: ``slots``
    concurrent, one token per request per step, each step ``step_s`` of
    virtual time.  Capacity = slots / (step_s × budget) requests/sec."""

    def __init__(self, clock, slots=2, step_s=0.05, default_budget=4):
        self.clock = clock
        self.slots = slots
        self.step_s = step_s
        self.default_budget = default_budget
        self.submit_t: list[float] = []
        self.arrival_t: list[float] = []
        self.budgets: list[int] = []
        self.outputs: list[list[int]] = []
        self._first: list[float | None] = []
        self.pending: list[int] = []
        self.active: list[int] = []

    def submit(self, tokens, *, max_new=None, attention_mask=None,
               label=None, arrival=None):
        rid = len(self.submit_t)
        now = self.clock.now()
        self.submit_t.append(now)
        self.arrival_t.append(arrival if arrival is not None else now)
        self.budgets.append(max_new or self.default_budget)
        self.outputs.append([])
        self._first.append(None)
        self.pending.append(rid)
        return rid

    def has_work(self):
        return bool(self.pending or self.active)

    def step(self):
        self.clock.advance(self.step_s)
        while self.pending and len(self.active) < self.slots:
            self.active.append(self.pending.pop(0))
        finished = []
        for rid in list(self.active):
            self.outputs[rid].append(100 + len(self.outputs[rid]))
            if self._first[rid] is None:
                self._first[rid] = self.clock.now()
            if len(self.outputs[rid]) >= self.budgets[rid]:
                self.active.remove(rid)
                finished.append(rid)
        return finished

    def finalize(self):
        return {}

    def first_token_wall(self, rid):
        return self._first[rid]

    def output(self, rid):
        return self.outputs[rid]


def _fake_sweep(cfg, n_req=24, slots=2, step_s=0.05):
    """One whole sweep on the fake, virtual time only."""
    vc = VirtualClock()
    return sweep_qps(
        lambda: EngineTarget(ClockedFakeSession(vc, slots=slots, step_s=step_s)),
        [[1, 2, 3]] * n_req, cfg,
        clock=vc.now, wait=vc.advance, emit=False,
    )


def test_open_loop_drive_builds_queues():
    """Arrivals never wait for completions: offering 10× the fake's
    capacity piles requests into the queue, and TTFT measured from
    ARRIVAL grows with arrival index."""
    vc = VirtualClock()
    sess = ClockedFakeSession(vc, slots=1, step_s=0.1, default_budget=1)
    # capacity 10 tokens/s => 10 req/s at budget 1; offer 100/s
    sched = [i * 0.01 for i in range(12)]
    rows, wall_s = drive_open_loop(
        EngineTarget(sess), [[1]] * 12, sched, clock=vc.now, wait=vc.advance,
    )
    assert all(r["finished"] for r in rows)
    ttfts = [r["ttft_s"] for r in rows]
    assert ttfts[-1] > ttfts[0] * 5  # the queue genuinely built
    assert queue_growing(rows, wall_s)


def test_sweep_deterministic_and_knee_on_fake():
    """Same seed + config → identical sweep summaries (verdicts, curves,
    knee), twice over; the knee lands where offered rate crosses the
    fake's capacity."""
    cfg = LoadgenConfig(qps_grid=(1.0, 4.0, 40.0), ttft_slo_ms=400.0)
    s1 = _fake_sweep(cfg)
    s2 = _fake_sweep(cfg)
    assert s1 == s2
    # capacity is 2 slots / (0.05 s × 4 tokens) = 10 req/s: 1 and 4 QPS
    # track, 40 QPS has saturated
    assert [p["queue_growing"] for p in s1["points"]] == [False, False, True]
    assert s1["knee_qps"] == 40.0
    assert s1["points"][0]["slo_attainment"] == 1.0
    assert s1["points"][2]["slo_attainment"] < 0.5
    # a different seed moves the schedule (the curve numbers shift)
    s3 = _fake_sweep(LoadgenConfig(qps_grid=(1.0, 4.0, 40.0),
                                   ttft_slo_ms=400.0, seed=9))
    assert s3["points"] != s1["points"]


def test_open_loop_sees_collapse_closed_loop_cannot():
    """THE acceptance disagreement: the same config measured closed-loop
    (submit all, drain — offered rate capped by service rate) reads
    healthy, while the open-loop sweep at an offered rate above capacity
    reports unbounded queue growth.  Two verdicts, pinned to disagree."""
    # closed-loop pass: all 16 requests at t=0, drain to completion
    vc = VirtualClock()
    sess = ClockedFakeSession(vc, slots=2, step_s=0.05)
    for _ in range(16):
        sess.submit([1, 2, 3])
    while sess.has_work():
        sess.step()
    closed_wall = vc.now()
    closed_qps = 16 / closed_wall
    assert closed_qps > 9.0  # ~capacity: the closed-loop number is healthy
    # open-loop pass: offer 4× capacity — the same config collapses
    cfg = LoadgenConfig(qps_grid=(40.0,), ttft_slo_ms=400.0)
    point = _fake_sweep(cfg, n_req=16)["points"][0]
    assert point["queue_growing"] is True
    assert point["slo_attainment"] < 1.0
    # the open-loop driver still pushed tokens at device rate — it is the
    # LATENCY verdict that collapses, which closed-loop cannot see
    assert point["achieved_qps"] > 9.0


def test_open_loop_matches_closed_loop_tokens_on_fake():
    """Determinism contract at the fake tier: arrival timing moves
    latency, never tokens — open-loop outputs equal the closed-loop
    drain's."""
    vc = VirtualClock()
    oracle = ClockedFakeSession(vc, slots=2, step_s=0.05)
    budgets = [2, 4, 3, 5, 1, 4, 2, 3]
    for b in budgets:
        oracle.submit([1], max_new=b)
    while oracle.has_work():
        oracle.step()
    vc2 = VirtualClock()
    sess = ClockedFakeSession(vc2, slots=2, step_s=0.05)
    sched = arrival_schedule("bursty", qps=30.0, n=8, seed=1)
    drive_open_loop(
        EngineTarget(sess), [[1]] * 8, sched, budgets=budgets,
        clock=vc2.now, wait=vc2.advance,
    )
    assert [sess.output(r) for r in range(8)] == [
        oracle.output(r) for r in range(8)
    ]


def test_drive_open_loop_wall_cap_reports_unsubmitted_tail():
    """A capped run reports what never got submitted as data (submitted=
    False rows), not an error — and the length validation still bites."""
    vc = VirtualClock()
    sess = ClockedFakeSession(vc, slots=1, step_s=0.5, default_budget=8)
    sched = [0.0, 0.1, 50.0]
    rows, wall_s = drive_open_loop(
        EngineTarget(sess), [[1]] * 3, sched, clock=vc.now, wait=vc.advance,
        max_wall_s=2.0,
    )
    assert rows[2]["submitted"] is False and rows[2]["finished"] is False
    assert wall_s <= 3.0
    with pytest.raises(ValueError, match="arrivals for"):
        drive_open_loop(EngineTarget(sess), [[1]] * 2, [0.0])


# ---------------------------------------------------------------------------
# the router target: shed accounting + arrival threading (fake replicas)
# ---------------------------------------------------------------------------


def test_router_target_threads_arrival_and_counts_shed():
    from distributed_llms_example_tpu.serving.router import (
        ReplicaRouter,
        RouterConfig,
    )
    from tests.test_router import FakeEngine

    router = ReplicaRouter(
        [FakeEngine(), FakeEngine()], None,
        RouterConfig(log_every_ticks=0, max_queue=4, shed_policy="shed"),
    )
    target = RouterTarget(router)
    # one burst: every arrival due before the first tick, so the queue
    # bound (4) trips before dispatch can drain it
    sched = [1e-4] * 10
    rows, wall_s = drive_open_loop(target, [[1, 2]] * 10, sched)
    assert len(rows) == 10
    assert sum(r["shed"] for r in rows) > 0  # the queue bound shed some
    done = [r for r in rows if r["finished"]]
    assert done and all(r["ttft_s"] is not None for r in done)
    # arrival threading: the router rows carry the arrival→submit stage
    rrows = [r for r in router.request_rows() if not r["synthetic"]]
    assert all("arrival_s" in r and "queue_delay_ms" in r for r in rrows)
    assert all(r["queue_delay_ms"] >= 0 for r in rrows)
    point = summarize_point(
        rows, offered_qps=1000.0, ttft_slo_ms=500.0, wall_s=wall_s,
    )
    assert point["shed"] == sum(r["shed"] for r in rows)
    # shed requests saturate the point
    assert detect_knee([point]) == 1000.0


# ---------------------------------------------------------------------------
# schema round-trip: sweep events → JSONL → report section + strict gates
# ---------------------------------------------------------------------------


def _emit_fake_sweep_to(tmp_path, cfg, **kw):
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    sink_mod.install_sink(sink_mod.JsonlFileSink(path))
    try:
        vc = VirtualClock()
        summary = sweep_qps(
            lambda: EngineTarget(ClockedFakeSession(vc, **kw)),
            [[1, 2, 3]] * 16, cfg, clock=vc.now, wait=vc.advance,
        )
    finally:
        sink_mod.current_sink().close()
        sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    return summary


def test_report_renders_sweep_from_jsonl_alone(tmp_path):
    """The acceptance pin: obs.report renders the QPS-sweep table and
    SLO attainment from the JSONL stream alone — no in-process state."""
    cfg = LoadgenConfig(qps_grid=(1.0, 4.0, 40.0), ttft_slo_ms=400.0)
    summary = _emit_fake_sweep_to(tmp_path, cfg)
    # every event round-trips the schema loader (schema_version stamped)
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records = [json.loads(line) for line in open(path)]
    assert all(r["schema_version"] == 1 for r in records)
    assert sum(r.get("event") == "loadgen_point" for r in records) == 3
    assert sum(r.get("event") == "loadgen_summary" for r in records) == 1
    report = build_report(str(tmp_path))
    lg = report["loadgen"]
    assert lg["knee_qps"] == summary["knee_qps"] == 40.0
    assert [p["offered_qps"] for p in lg["points"]] == [1.0, 4.0, 40.0]
    assert lg["best_slo_attainment"] == 1.0
    assert lg["best_ttft_p99_ms"] is not None
    md = render_markdown(report)
    assert "## Open-loop load sweep" in md
    assert "**40 QPS** (first saturated offered rate)" in md
    assert "| offered QPS |" in md and "| 40 |" in md


def test_report_bare_points_without_summary_still_render(tmp_path):
    """A run killed mid-sweep leaves loadgen_point events but no
    summary — the curve still renders (knee unknown)."""
    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir, exist_ok=True)
    p = summarize_point(
        [{"arrival_s": 0.0, "queue_delay_s": 0.0, "ttft_s": 0.02,
          "finished": True, "shed": False}],
        offered_qps=2.0, ttft_slo_ms=100.0, wall_s=1.0,
    )
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        f.write(json.dumps({
            "schema_version": 1, "event": "loadgen_point",
            "process": "poisson", "seed": 0, **p,
        }) + "\n")
    lg = build_report(str(tmp_path))["loadgen"]
    assert lg["knee_qps"] is None
    assert len(lg["points"]) == 1
    assert "not reached on this grid" in render_markdown(
        build_report(str(tmp_path))
    )


def test_strict_gates_cut_both_ways(tmp_path, capsys):
    from distributed_llms_example_tpu.obs.report import main as report_main

    cfg = LoadgenConfig(qps_grid=(1.0, 4.0, 40.0), ttft_slo_ms=400.0)
    _emit_fake_sweep_to(tmp_path, cfg)
    d = str(tmp_path)
    # attainment: the best point reaches 1.0 → a 0.99 floor passes
    assert report_main(
        [d, "--strict", "--min-slo-attainment", "0.99", "--json"]
    ) == 0
    # p99: the best measured point is well under a generous ceiling
    assert report_main(
        [d, "--strict", "--max-p99-ttft-ms", "5000", "--json"]
    ) == 0
    # ...and over a 1 ms ceiling fails with the measured value named
    assert report_main(
        [d, "--strict", "--max-p99-ttft-ms", "1", "--json"]
    ) == 1
    assert "exceeds" in capsys.readouterr().err


def test_strict_gate_fails_without_loadgen_measurement(tmp_path, capsys):
    """THE acceptance pin: --strict --min-slo-attainment on a run with no
    loadgen measurement fails — missing must never read as a pass."""
    from distributed_llms_example_tpu.obs.report import main as report_main

    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir, exist_ok=True)
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        f.write(json.dumps({"schema_version": 1, "step": 1, "loss": 1.0}) + "\n")
    d = str(tmp_path)
    assert report_main([d, "--strict", "--json"]) == 0  # clean without the gate
    assert report_main(
        [d, "--strict", "--min-slo-attainment", "0.5", "--json"]
    ) == 1
    assert "no loadgen measurement" in capsys.readouterr().err
    assert report_main(
        [d, "--strict", "--max-p99-ttft-ms", "500", "--json"]
    ) == 1
    assert "no measured p99" in capsys.readouterr().err


def test_strict_p99_gate_fails_on_fully_collapsed_run(tmp_path, capsys):
    """Every point collapsed (nothing finished anywhere): the p99 gate
    fails as a MISSING measurement — None percentiles never compare."""
    from distributed_llms_example_tpu.obs.report import main as report_main

    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir, exist_ok=True)
    p = summarize_point(
        [{"arrival_s": 0.0, "queue_delay_s": None, "ttft_s": None,
          "finished": False, "shed": False}],
        offered_qps=8.0, ttft_slo_ms=100.0, wall_s=1.0,
    )
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        f.write(json.dumps({
            "schema_version": 1, "event": "loadgen_point",
            "process": "poisson", "seed": 0, **p,
        }) + "\n")
    rc = report_main(
        [str(tmp_path), "--strict", "--max-p99-ttft-ms", "500", "--json"]
    )
    assert rc == 1
    assert "no measured p99" in capsys.readouterr().err


def test_obs_gate_passes_loadgen_flags_through(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "obs_gate",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "obs_gate.py"),
    )
    obs_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_gate)
    seen = {}

    def fake_main(flags):
        seen["flags"] = flags
        return 0

    import distributed_llms_example_tpu.obs.report as report_mod

    monkeypatch.setattr(report_mod, "main", fake_main)
    assert obs_gate.main([
        str(tmp_path), "--min-slo-attainment", "0.8",
        "--max-p99-ttft-ms", "750",
    ]) == 0
    flags = seen["flags"]
    i = flags.index("--min-slo-attainment")
    assert flags[i + 1] == "0.8"
    j = flags.index("--max-p99-ttft-ms")
    assert flags[j + 1] == "750.0"
    # off by default: no loadgen flags injected
    assert obs_gate.main([str(tmp_path)]) == 0
    assert "--min-slo-attainment" not in seen["flags"]


def test_bench_diff_directions_for_loadgen_leaves():
    spec = importlib.util.spec_from_file_location(
        "bench_diff",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "bench_diff.py"),
    )
    bench_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_diff)
    d = bench_diff.direction_of
    # curve quality: knee moving right / more goodput / attainment = better
    assert d("loadgen.knee_qps") == 1
    assert d("loadgen.points.goodput_qps") == 1
    assert d("loadgen.points.slo_attainment") == 1
    assert d("loadgen.points.achieved_qps") == 1
    # tail latency and queueing delay: lower is better
    assert d("loadgen.points.ttft_p99_ms") == -1
    assert d("loadgen.points.queue_delay_p99_ms") == -1
    # the experiment's shape knobs are config, never regressions —
    # including max_wall_s, which would otherwise match "wall_s"
    assert d("loadgen.qps_grid") == 0
    assert d("loadgen.requests_per_point") == 0
    assert d("loadgen.points.offered_qps") == 0
    assert d("cfg.max_wall_s") == 0
    assert d("cfg.burst_size") == 0
    # ...while a genuine wall measurement still gates lower-better
    assert d("loadgen.points.wall_s") == -1
    # prefix-cache leaves: hit rate and tokens saved are higher-better,
    # the LRU byte ceiling is config
    assert d("serve_prefix.hit_rate") == 1
    assert d("serve_prefix.prefill_tokens_saved") == 1
    assert d("cfg.prefix_cache_budget") == 0


# ---------------------------------------------------------------------------
# the real engine: closed-loop arrival stamps (fast) + open-loop
# collapse and token determinism (slow tier)
# ---------------------------------------------------------------------------


def _engine(lm, mesh, *, slots=4, max_new=6, src=16, slo_ms=0.0,
            log_every=0):
    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
    )

    return ServingEngine(
        lm.module, lm.config, mesh,
        ServeConfig(max_slots=slots, prefill_batch=slots,
                    max_new_tokens=max_new, max_source_length=src,
                    log_every_steps=log_every, ttft_slo_ms=slo_ms),
        is_seq2seq=lm.is_seq2seq,
    )


def test_closed_loop_serve_request_arrival_fields(mesh8, capsys):
    """Satellite 1: serve_request gains t_arrival_s + queue_delay_ms and
    serve_summary the queue-delay percentiles; closed-loop submits stamp
    arrival == submit, so the new stage reads 0 and every existing
    consumer stays green."""
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    lm = load_model("t5-test", load_weights=False)
    params = shard_params(lm.init_params(0), mesh8)
    eng = _engine(lm, mesh8, log_every=2)
    rng = np.random.RandomState(0)
    reqs = [list(rng.randint(3, 100, rng.randint(3, 10))) for _ in range(4)]
    capsys.readouterr()
    eng.generate(params, reqs)
    events = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    spans = [e for e in events if e.get("event") == "serve_request"]
    assert len(spans) == len(reqs)
    for e in spans:
        assert "t_arrival_s" in e and "queue_delay_ms" in e
        assert e["queue_delay_ms"] == 0.0  # closed-loop: arrival == submit
        # the two queueing stages decompose: arrival→submit + submit→admit
        assert e["t_arrival_s"] <= e["t_admit_s"]
    summary = next(e for e in events if e.get("event") == "serve_summary")
    assert summary["queue_delay_p50_ms"] == 0.0
    assert summary["queue_delay_p99_ms"] == 0.0
    window = next(e for e in events if e.get("event") == "serve_window")
    assert {"arrival_rate_per_sec", "service_rate_per_sec",
            "queue_growth"} <= set(window)


@pytest.mark.slow  # real compiled engine: one prefill+decode program, a
# closed-loop oracle pass and a 2-point open-loop sweep (~1 min on CPU)
def test_real_engine_open_loop_collapse_and_token_determinism(mesh8, capsys):
    """The acceptance criteria on a REAL tiny engine: (1) open-loop
    tokens are bit-identical to the closed-loop oracle at every offered
    rate (arrival timing moves latency, never tokens); (2) an offered
    rate far above the engine's measured capacity reports queueing
    collapse while the closed-loop measurement of the same config
    reports healthy throughput."""
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    lm = load_model("t5-test", load_weights=False)
    params = shard_params(lm.init_params(0), mesh8)
    rng = np.random.RandomState(3)
    reqs = [list(rng.randint(3, 100, rng.randint(3, 12))) for _ in range(8)]
    budgets = [int(b) for b in rng.randint(2, 7, len(reqs))]
    eng = _engine(lm, mesh8, slo_ms=10_000.0)
    # closed-loop oracle: healthy verdict + the token reference
    import time as _time

    t0 = _time.perf_counter()
    oracle = eng.generate(params, reqs, max_new=budgets)
    closed_wall = max(_time.perf_counter() - t0, 1e-9)
    closed_qps = len(reqs) / closed_wall
    # open-loop sweep: one rate the engine can absorb, one far past it
    cfg = LoadgenConfig(
        qps_grid=(max(closed_qps / 4, 0.1), closed_qps * 50),
        ttft_slo_ms=10_000.0, max_wall_s=max(closed_wall * 6, 5.0),
    )
    sessions = []

    def factory():
        sess = eng.open(params)
        sessions.append(sess)
        return EngineTarget(sess)

    summary = sweep_qps(factory, reqs, cfg, budgets=budgets)
    low, high = summary["points"]
    # (2) the disagreement: closed-loop reads healthy, the over-offered
    # open-loop point saturates (growing delay / unfinished tail)
    assert low["completed"] == len(reqs)
    assert high["queue_growing"] or high["unfinished"] > 0
    assert summary["knee_qps"] is not None
    # (1) determinism: both sweep points produced the oracle's tokens
    # for everything that ran to completion
    for sess in sessions:
        for rid in range(len(reqs)):
            out = sess.output(rid)
            if len(out) == budgets[rid]:  # ran to completion
                assert out == oracle[rid]


# ------------------------------------------------------- chatbot workload


def test_chatbot_workload_replayable_and_multi_turn():
    """The chatbot mix (prefix-cache bench workload): bit-replayable from
    its seed; turn t+1's prompt EXTENDS turn t's exactly (history grows,
    never rewrites — the property prefix matching feeds on); the shared
    fraction of sessions opens with one identical system prompt; and
    session keys group turns."""
    from distributed_llms_example_tpu.serving.loadgen import chatbot_requests

    reqs, keys = chatbot_requests(sessions=10, turns=4, seed=3)
    again, keys2 = chatbot_requests(sessions=10, turns=4, seed=3)
    assert reqs == again and keys == keys2
    other, _ = chatbot_requests(sessions=10, turns=4, seed=4)
    assert reqs != other
    assert len(reqs) == 40 and len(set(keys)) == 10
    # group by session, in turn order (the interleave is turn-major)
    by_session: dict = {}
    for req, key in zip(reqs, keys):
        by_session.setdefault(key, []).append(req)
    for turns in by_session.values():
        assert len(turns) == 4
        for a, b in zip(turns, turns[1:]):
            assert b[: len(a)] == a and len(b) > len(a)
    # 90% of sessions open with the SAME system prompt, the rest diverge
    openers = [tuple(t[0][:12]) for t in by_session.values()]
    top = max(set(openers), key=openers.count)
    assert openers.count(top) == 9
    # max_len caps the submitted prompt while history keeps growing
    capped, _ = chatbot_requests(sessions=2, turns=6, seed=5, max_len=20)
    assert max(len(r) for r in capped) == 20
    with pytest.raises(ValueError):
        chatbot_requests(sessions=0, turns=4)
    with pytest.raises(ValueError):
        chatbot_requests(sessions=2, turns=4, shared_frac=1.5)
