"""The obs/ telemetry stack (ISSUE 2).

Acceptance pins: span nesting/percentiles on a fake clock; MFU math
against a hand-computed FLOP count; collective-traffic accounting against
a known FSDP HLO (reduce-scatter vs all-reduce split); the Valohai stdout
byte-parity contract; the MetricLogger cadence fix; and the end-to-end
``--obs jsonl`` stream whose gradient all-gather/reduce-scatter byte
totals match the IR lint's independent accounting of the same compiled
step.  The heartbeat's real multi-process leg rides the slow tier next to
tests/test_multiprocess.py; its skew math is unit-tested here.

This module is tier-1 (not slow) and budgeted: the instrumentation it
tests must itself be cheap (test_span_recording_time_budget).
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import (
    CheckpointConfig,
    MeshConfig,
    TrainConfig,
)
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.gauges import (
    collective_traffic,
    mfu,
    training_flops_estimate,
)
from distributed_llms_example_tpu.obs.heartbeat import Heartbeat, detect_laggards
from distributed_llms_example_tpu.obs.profile import ProfileController, parse_profile_steps
from distributed_llms_example_tpu.obs.spans import SpanRecorder, percentiles
from distributed_llms_example_tpu.utils.jsonlog import MetricLogger, log_json


@pytest.fixture(autouse=True)
def _default_sink():
    """Every test starts and ends on the plain stdout sink, whatever a
    previous test (or a Trainer construction) installed."""
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


# ---------------------------------------------------------------------------
# spans: fake clock, nesting, percentiles, straggler flag
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def test_span_nesting_and_window_summary_on_fake_clock():
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    for step_time in (0.1, 0.1, 0.1, 0.5):  # one fat straggler step
        with rec.span("step_dispatch"):
            with rec.span("data_wait"):  # nested span
                clock.advance(step_time / 2)
            clock.advance(step_time / 2)
        rec.step_complete()
    s = rec.summary()
    assert s["window_steps"] == 4
    assert s["step_ms_p50"] == pytest.approx(100.0)
    assert s["step_ms_max"] == pytest.approx(500.0)
    assert s["straggler"] is True  # 500 > 2 × 100
    assert s["spans"]["step_dispatch"]["count"] == 4
    # nested data_wait time is counted inside BOTH spans (nesting, not
    # exclusive attribution)
    assert s["spans"]["data_wait"]["total_ms"] == pytest.approx(400.0)
    assert s["spans"]["step_dispatch"]["total_ms"] == pytest.approx(800.0)
    # summary resets the window
    assert rec.summary() is None
    with rec.span("eval"):
        clock.advance(1.0)
    rec.step_complete()
    s2 = rec.summary()
    assert s2["window_steps"] == 1 and "step_dispatch" not in s2["spans"]
    assert s2["straggler"] is False


def test_mark_step_start_excludes_eval_time():
    """Checkpoint/eval wall time between steps rides its own span, not
    the next step's ring-buffer duration (which would flag every healthy
    eval cadence as a straggler)."""
    clock = FakeClock()
    rec = SpanRecorder(clock=clock)
    clock.advance(0.1)
    rec.step_complete()
    with rec.span("eval"):
        clock.advance(5.0)  # a fat eval after the step
    rec.mark_step_start()
    clock.advance(0.1)
    rec.step_complete()
    s = rec.summary()
    assert s["step_ms_max"] == pytest.approx(100.0)  # eval's 5 s excluded
    assert s["straggler"] is False
    assert s["spans"]["eval"]["total_ms"] == pytest.approx(5000.0)


def test_percentiles_nearest_rank():
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    p50, p95, p0 = percentiles(vals, (0.5, 0.95, 0.0))
    assert (p50, p95, p0) == (3.0, 5.0, 1.0)
    assert percentiles([], (0.5,)) == [0.0]


def test_span_recording_time_budget():
    """The instrumentation must be hot-path cheap: 20k span enter/exits
    plus step bookkeeping in well under a second (it measures host clock
    reads and dict updates, nothing else)."""
    rec = SpanRecorder()
    t0 = time.perf_counter()
    for _ in range(20_000):
        with rec.span("step_dispatch"):
            pass
        rec.step_complete()
    assert rec.summary()["window_steps"] == 20_000
    assert time.perf_counter() - t0 < 1.0


# ---------------------------------------------------------------------------
# gauges: MFU math, HBM gating, collective accounting on a known FSDP HLO
# ---------------------------------------------------------------------------

def test_mfu_math_hand_computed():
    # tiny model by hand: N=1000 params, 64 tokens/step → 6·N·T FLOPs
    assert training_flops_estimate(1000, 64) == 6.0 * 1000 * 64
    # 384k FLOPs over 0.5 s on 4 chips of 1 MFLOP/s peak:
    # 384e3 / (0.5 · 4 · 1e6) = 0.192
    assert mfu(384_000.0, 0.5, 4, 1e6) == pytest.approx(0.192)
    assert mfu(1.0, 0.0, 4, 1e6) == 0.0  # degenerate window


def test_hbm_stats_gated_on_cpu():
    from distributed_llms_example_tpu.obs.gauges import hbm_stats

    # CPU PJRT reports no memory_stats: the gauge must say nothing, not 0
    assert hbm_stats() is None


# A hand-written FSDP-style step: params sharded 8-way.  The gradient
# reduce-scatter (full 2048×512 f32 tree leaf in, 1/8 shard out) and the
# forward param all-gather match the model tree; the small all-reduce is
# the loss scalar (activation traffic); the big all-reduce is the SAME
# gradient leaf all-reduced — the 2× traffic anti-pattern the account
# exists to expose next to its reduce-scattered twin.
_FSDP_HLO = """\
HloModule fsdp_step

ENTRY %main {
  %pshard = bf16[256,512]{1,0} parameter(0)
  %gfull = f32[2048,512]{1,0} parameter(1)
  %act = f32[8,128]{1,0} parameter(2)
  %ag.params = bf16[2048,512]{1,0} all-gather(bf16[256,512]{1,0} %pshard), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %rs.grads = f32[256,512]{1,0} reduce-scatter(f32[2048,512]{1,0} %gfull), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, to_apply=%add
  %ar.grads = f32[2048,512]{1,0} all-reduce(f32[2048,512]{1,0} %gfull), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
  %ar.loss = f32[] all-reduce(f32[] %act), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
  ROOT %t = (f32[256,512]{1,0}) tuple(%rs.grads)
}
"""


def test_collective_traffic_fsdp_split():
    acct = collective_traffic(_FSDP_HLO, [2048 * 512], mesh_size=8)
    # reduce-scatter: gradient traffic, sized by its per-device RESULT
    assert acct["reduce-scatter"]["gradient_bytes"] == 256 * 512 * 4
    assert acct["reduce-scatter"]["activation_bytes"] == 0
    # the all-reduce twin of the same gradient leaf is gradient traffic
    # (2048·512 f32) — vs the loss-scalar all-reduce on the activation side
    assert acct["all-reduce"]["gradient_bytes"] == 2048 * 512 * 4
    assert acct["all-reduce"]["activation_bytes"] == 4
    # the forward param gather moves the model tree too
    assert acct["all-gather"]["gradient_bytes"] == 2048 * 512 * 2
    assert acct["gradient_bytes"] == (
        256 * 512 * 4 + 2048 * 512 * 4 + 2048 * 512 * 2
    )
    assert acct["activation_bytes"] == 4
    assert acct["total_bytes"] == acct["gradient_bytes"] + acct["activation_bytes"]
    # and the reduce-scatter vs all-reduce split is visible: the same
    # gradient bytes cost 8× less scattered than replicated
    assert acct["all-reduce"]["gradient_bytes"] == 8 * acct["reduce-scatter"]["gradient_bytes"]


# ---------------------------------------------------------------------------
# acceptance: obs account == IR lint accounting on the SAME compiled step
# ---------------------------------------------------------------------------

_STEP_ARGS = dict(
    global_batch=8, src_len=32, tgt_len=16, dtype="bfloat16",
    remat=False, remat_policy="full", grad_accum_steps=1,
)


@pytest.fixture(scope="module")
def compiled_t5_fsdp():
    """One AOT compile (the shared recipe) serving every test below —
    and byte-identical to what the Trainer's gauge pass compiles for the
    same config, since both call the same recipe with the same args."""
    from distributed_llms_example_tpu.utils.memory_audit import (
        aot_compile_train_step,
    )

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    compiled, lm, a_params, _, _ = aot_compile_train_step(
        "t5-test", mesh, **_STEP_ARGS
    )
    elems = [int(np.prod(x.shape)) for x in jax.tree.leaves(a_params)]
    return compiled.as_text(), elems, mesh


def _merge_async(by_op: dict) -> dict:
    out: dict[str, int] = {}
    for op, b in by_op.items():
        base = op[: -len("-start")] if op.endswith("-start") else op
        out[base] = out.get(base, 0) + b
    return out


def test_comm_account_matches_ir_lint_census(compiled_t5_fsdp):
    from distributed_llms_example_tpu.analysis.ir_lint import scan_hlo_text

    text, elems, mesh = compiled_t5_fsdp
    acct = collective_traffic(text, elems, mesh.size)
    findings = scan_hlo_text(
        text, mesh_axes=dict(mesh.shape), param_element_counts=elems
    )
    census = next(f for f in findings if f.code == "collective-census")
    total_by_op = _merge_async(census.context["bytes_by_op"])
    grad_by_op = _merge_async(census.context["gradient_bytes_by_op"])
    assert total_by_op, "compiled fsdp step must contain collectives"
    for op, totals in total_by_op.items():
        slot = acct[op]
        assert slot["gradient_bytes"] + slot["activation_bytes"] == totals
        # the acceptance pin: gradient all-gather / reduce-scatter byte
        # totals agree between the runtime account and the IR lint
        assert slot["gradient_bytes"] == grad_by_op.get(op, 0)
    assert acct["gradient_bytes"] > 0  # an fsdp step moves the model tree


def test_obs_jsonl_stream_without_trainer(tmp_path):
    """Fast-tier wiring check: a TrainerObs driven by hand produces the
    same JSONL stream shape the Trainer does — window summaries with
    spans + MFU, heartbeat, schema stamps — without paying a train-step
    compile (the full end-to-end run is the slow-tier test below)."""
    from distributed_llms_example_tpu.obs import TrainerObs

    cfg = TrainConfig(
        output_dir=str(tmp_path), log_every_steps=2, obs="jsonl",
        obs_heartbeat_steps=2,
    )
    obs = TrainerObs(cfg, start_step=0)
    obs.flops_per_step = 1e9  # as the gauge compile would have set
    for step in (1, 2):
        with obs.step_span():
            pass
        obs.on_step(step, epoch=0, metrics={})
    log_json({"step": 2, "loss": 0.5})
    sink_mod.current_sink().close()
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records = [json.loads(line) for line in open(path)]
    assert all(r["schema_version"] == 1 for r in records)
    window = next(r for r in records if r.get("event") == "obs_window")
    assert {"step_ms_p50", "step_ms_p95", "step_ms_max", "straggler"} <= set(window)
    assert "step_dispatch" in window["spans"] and window["mfu"] > 0
    assert any(r.get("event") == "heartbeat" for r in records)
    assert any(r.get("step") == 2 and "loss" in r for r in records)


@pytest.mark.slow  # one full Trainer construction + two compiles (~35s):
# the fast tier keeps the same acceptance equality via the module fixture
# (test_comm_account_matches_ir_lint_census) and the stream-shape check
# above; this leg proves the real --obs jsonl loop end to end
def test_trainer_obs_jsonl_stream(tmp_path, compiled_t5_fsdp):
    """The end-to-end acceptance run: --obs jsonl on the CPU demo config
    produces a JSONL stream with per-step span windows, an MFU gauge, and
    a collective-traffic account equal to the IR lint's accounting of the
    same compiled step (the module fixture: same recipe, same args)."""
    from distributed_llms_example_tpu.analysis.ir_lint import scan_hlo_text
    from distributed_llms_example_tpu.train.trainer import Trainer

    text, elems, mesh = compiled_t5_fsdp
    rng = np.random.RandomState(0)
    recs = [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(12)),
            "summary": f"w{rng.randint(40)}",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=1,
        evaluation_steps=0,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=2,
        num_beams=1,
        tokenizer="byte",
        mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        obs="jsonl",
        obs_heartbeat_steps=2,
    )
    trainer = Trainer(cfg, train_records=recs)
    trainer.save_final = lambda: None  # the stream, not the artifact
    result = trainer.train()
    assert result["steps"] == 2

    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records = [json.loads(line) for line in open(path)]
    assert all(r["schema_version"] == 1 for r in records)
    by_event: dict[str, list[dict]] = {}
    for r in records:
        by_event.setdefault(r.get("event", "metric"), []).append(r)

    # per-step spans + percentiles + MFU ride the window summaries
    window = by_event["obs_window"][0]
    assert {"step_ms_p50", "step_ms_p95", "step_ms_max", "straggler"} <= set(window)
    assert {"data_wait", "step_dispatch", "device_sync"} <= set(window["spans"])
    assert window["mfu"] > 0
    # the step-time budget account (ISSUE 9 acceptance, on the REAL
    # trainer loop): components sum to the measured wall within 5% —
    # i.e. the unattributed remainder stays under tolerance — and the
    # budget layer's own probe charged device_busy at the cadence
    budgets = by_event["step_budget"]
    assert budgets, "budget layer must close every logging window"
    from distributed_llms_example_tpu.obs.budget import COMPONENTS

    for acct in budgets:
        total = sum(acct[f"{c}_ms"] for c in COMPONENTS)
        assert total == pytest.approx(acct["wall_ms"], rel=0.01)
        assert acct["additivity_ok"], acct
        assert acct["accounted_frac"] >= 0.95
        assert 0.0 <= acct["dispatch_efficiency"] <= 1.0
        # a healthy async loop must not trip the host-blocking tripwire
        assert acct["offcadence_sync_suspect"] is False
    assert any(a["device_busy_ms"] > 0 for a in budgets)
    # trace capture rode the same run: span instances + step marks for
    # the Perfetto export, bulk (file-channel-only) records
    traces = by_event["trace_spans"]
    assert traces and all("steps" in t for t in traces)
    span_names = {s[0] for t in traces for s in t["spans"]}
    assert {"step_dispatch", "device_sync"} <= span_names
    # the step-cadence metric lines tee into the same stream
    assert any("loss" in r and "step" in r for r in by_event["metric"])
    # heartbeat (single process: trivially zero skew, but alive)
    hb = by_event["heartbeat"][0]
    assert hb["process_count"] == 1 and hb["skew_steps"] == 0

    # the acceptance equality: the emitted account vs the IR lint's
    # independent scan of the same compiled step
    gauges = by_event["obs_gauges"][0]
    assert gauges["flops_per_step"] > 0
    emitted = gauges["comm"]
    census = next(
        f
        for f in scan_hlo_text(
            text, mesh_axes=dict(mesh.shape), param_element_counts=elems
        )
        if f.code == "collective-census"
    )
    grad_by_op = _merge_async(census.context["gradient_bytes_by_op"])
    total_by_op = _merge_async(census.context["bytes_by_op"])
    for op in ("all-gather", "reduce-scatter"):
        slot = emitted.get(op)
        if slot is None:
            assert grad_by_op.get(op, 0) == 0
            continue
        assert slot["gradient_bytes"] == grad_by_op.get(op, 0)
        assert slot["gradient_bytes"] + slot["activation_bytes"] == total_by_op[op]
    assert emitted["gradient_bytes"] > 0


# ---------------------------------------------------------------------------
# satellite (ISSUE 3): the ROADMAP reduce-scatter smell as a pure predicate
# over the gradient-byte account, pinned on a real compiled FSDP step
# ---------------------------------------------------------------------------

def test_reduce_scatter_smell_pure_predicate():
    from distributed_llms_example_tpu.analysis.ir_lint import (
        account_gradient_bytes_by_op,
        reduce_scatter_smell,
    )

    fsdp = {"fsdp": 8, "data": 1}
    # healthy: gradients ride reduce-scatter → no finding
    assert reduce_scatter_smell(
        {"reduce-scatter": 64 << 20, "all-reduce": 4}, fsdp
    ) is None
    # the 2× smell: the same bytes all-REDUCED instead
    f = reduce_scatter_smell({"all-reduce": 64 << 20, "reduce-scatter": 0}, fsdp)
    assert f is not None and f.code == "gradient-all-reduce-not-reduce-scatter"
    assert f.context["all_reduce_gradient_bytes"] == 64 << 20
    # async -start forms fold into their base op
    assert reduce_scatter_smell({"all-reduce-start": 64 << 20}, fsdp) is not None
    # not an fsdp mesh → gradients are SUPPOSED to all-reduce (pure DP)
    assert reduce_scatter_smell({"all-reduce": 64 << 20}, {"data": 8}) is None
    # below the noise floor → quiet
    assert reduce_scatter_smell({"all-reduce": 1024}, fsdp) is None
    # the obs runtime account (per-op dicts) feeds the SAME predicate
    acct = {
        "all-reduce": {"count": 2, "gradient_bytes": 64 << 20, "activation_bytes": 4},
        "reduce-scatter": {"count": 0, "gradient_bytes": 0, "activation_bytes": 0},
        "total_bytes": (64 << 20) + 4,
        "gradient_bytes": 64 << 20,
        "activation_bytes": 4,
    }
    by_op = account_gradient_bytes_by_op(acct)
    assert by_op == {"all-reduce": 64 << 20, "reduce-scatter": 0}
    assert reduce_scatter_smell(by_op, fsdp) is not None


def test_reduce_scatter_smell_pinned_on_compiled_fsdp_step(compiled_t5_fsdp):
    """The predicate over the REAL compiled FSDP step.  Pinned behavior on
    this backend: the CPU SPMD partitioner lowers the fsdp gradient
    reduction as all-reduce (+ dynamic-slice), NOT reduce-scatter — i.e.
    the compiled step genuinely exhibits the 2× gradient-traffic pattern
    the smell hunts, so with the noise floor dropped the predicate MUST
    fire, and it must fire identically over the IR census and the obs
    runtime account (same parser, same classification)."""
    from distributed_llms_example_tpu.analysis.ir_lint import (
        account_gradient_bytes_by_op,
        reduce_scatter_smell,
        scan_hlo_text,
    )

    text, elems, mesh = compiled_t5_fsdp
    census = next(
        f
        for f in scan_hlo_text(
            text, mesh_axes=dict(mesh.shape), param_element_counts=elems
        )
        if f.code == "collective-census"
    )
    grad_by_op = census.context["gradient_bytes_by_op"]
    assert grad_by_op.get("all-reduce", 0) > 0  # the pattern is really there
    f = reduce_scatter_smell(grad_by_op, dict(mesh.shape), min_bytes=0)
    assert f is not None and f.code == "gradient-all-reduce-not-reduce-scatter"
    # the same verdict from the runtime account of the same program
    acct = collective_traffic(text, elems, mesh.size)
    f2 = reduce_scatter_smell(
        account_gradient_bytes_by_op(acct), dict(mesh.shape), min_bytes=0
    )
    assert f2 is not None
    assert f2.context == f.context


# ---------------------------------------------------------------------------
# satellite: MetricLogger cadence fix + flush
# ---------------------------------------------------------------------------

def test_metric_logger_no_step0_fire_and_flush(capsys):
    logger = MetricLogger(every=3)
    logger.step(0, 1.0, tokens=10)  # the old bug: fired here, empty window
    assert capsys.readouterr().out == ""
    for s in (1, 2, 3):
        logger.step(s, 0.5, lr=0.1, tokens=10)
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1 and lines[0]["step"] == 3
    assert lines[0]["steps_per_sec"] > 0
    # partial final window: two more steps, then flush
    logger.step(4, 0.4, lr=0.1, tokens=10)
    logger.step(5, 0.3, lr=0.1, tokens=10)
    assert _json_lines(capsys.readouterr().out) == []
    logger.flush(5, epoch=0)
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1
    assert lines[0]["step"] == 5 and lines[0]["loss"] == 0.3 and lines[0]["epoch"] == 0
    # flush is idempotent: the window is already drained
    logger.flush(5)
    assert _json_lines(capsys.readouterr().out) == []


# ---------------------------------------------------------------------------
# satellite: log_json sink routing, schema_version, stdout byte parity
# ---------------------------------------------------------------------------

def _legacy_line(metrics: dict) -> str:
    """The pre-obs log_json serialization, verbatim (the Valohai metadata
    contract this PR must not move a byte)."""
    def conv(v):
        if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
            v = v.item()
        if isinstance(v, float):
            return round(v, 6)
        return v

    return json.dumps({k: conv(v) for k, v in metrics.items()})


def test_log_json_stdout_byte_parity(capsys):
    import jax.numpy as jnp

    metrics = {
        "step": 7,
        "loss": jnp.float32(0.123456789),  # 0-d device array, like the trainer
        "learning_rate": 5e-5,
        "tokens_per_sec": 12345.678901234,
        "event": "parity",
    }
    log_json(metrics)
    out = capsys.readouterr().out
    assert out == _legacy_line(metrics) + "\n"


def test_jsonl_file_sink_schema_version(tmp_path, capsys):
    path = str(tmp_path / "obs" / "m.jsonl")
    sink_mod.install_sink(
        sink_mod.TeeSink([sink_mod.StdoutSink(), sink_mod.JsonlFileSink(path)])
    )
    log_json({"event": "x", "v": 1})
    # stdout unchanged (no schema_version: the platform contract)...
    assert _json_lines(capsys.readouterr().out) == [{"event": "x", "v": 1}]
    # ...the file record is stamped
    sink_mod.current_sink().close()
    rec = json.loads(open(path).read())
    assert rec == {"schema_version": 1, "event": "x", "v": 1}


def test_build_sink_modes(tmp_path):
    assert isinstance(sink_mod.build_sink("stdout", str(tmp_path)), sink_mod.StdoutSink)
    assert isinstance(sink_mod.build_sink("off", str(tmp_path)), sink_mod.StdoutSink)
    tee = sink_mod.build_sink("jsonl", str(tmp_path))
    assert isinstance(tee, sink_mod.TeeSink)
    tee.close()


# ---------------------------------------------------------------------------
# heartbeat: skew math (pure) + single-process beat; the 2-process leg is
# slow-tier (the same multiprocess CPU rendezvous as test_multiprocess.py)
# ---------------------------------------------------------------------------

def test_detect_laggards_pure():
    out = detect_laggards(
        np.array([10, 10, 8]),
        np.array([100.0, 100.2, 103.0]),
        laggard_threshold_s=1.0,
    )
    assert out["skew_steps"] == 2
    assert out["min_step"] == 8 and out["max_step"] == 10
    assert out["arrival_spread_s"] == pytest.approx(3.0)
    assert out["laggards"] == [2]
    clean = detect_laggards(np.array([5]), np.array([10.0]))
    assert clean["skew_steps"] == 0 and clean["laggards"] == []


def test_heartbeat_single_process_beat(capsys):
    rec = Heartbeat(every_steps=4).beat(12)
    assert rec["process_count"] == 1 and rec["skew_steps"] == 0
    lines = _json_lines(capsys.readouterr().out)
    assert any(r.get("event") == "heartbeat" and r["step"] == 12 for r in lines)


@pytest.mark.slow
def test_heartbeat_two_process_skew(tmp_path):
    """Two real OS processes rendezvous (the test_multiprocess.py CPU
    mesh) and probe with different step counters and a delayed rank 1:
    process 0 must report the skew and the laggard."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = """
import json, os, sys, time
import jax
from distributed_llms_example_tpu.core.mesh import initialize_distributed
initialize_distributed(
    os.environ["HB_COORD"], 2, int(os.environ["HB_RANK"])
)
from distributed_llms_example_tpu.obs.heartbeat import Heartbeat
rank = jax.process_index()
if rank == 1:
    time.sleep(1.5)  # the straggler
rec = Heartbeat(every_steps=1, laggard_threshold_s=1.0).beat(7 + 2 * rank)
if rank == 0:
    print("HBREC " + json.dumps(rec))
"""
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
            "HB_COORD": f"127.0.0.1:{port}",
            "HB_RANK": str(rank),
        })
        for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK"):
            env.pop(k, None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = [p.communicate(timeout=300) for p in procs]
    assert all(p.returncode == 0 for p in procs), outs[0][1][-2000:] + outs[1][1][-2000:]
    line = next(ln for ln in outs[0][0].splitlines() if ln.startswith("HBREC "))
    rec = json.loads(line[len("HBREC "):])
    assert rec["process_count"] == 2
    assert rec["skew_steps"] == 2  # ranks probed at steps 7 and 9
    assert rec["arrival_spread_s"] >= 1.0  # rank 1 slept 1.5 s
    assert rec["laggards"] == [1]


# ---------------------------------------------------------------------------
# profiler: window spec parsing + trigger-file capture
# ---------------------------------------------------------------------------

def test_parse_profile_steps_forms():
    assert parse_profile_steps(3) == 3
    assert parse_profile_steps("3") == 3
    assert parse_profile_steps("100:105") == (100, 105)
    assert parse_profile_steps(0) is None
    assert parse_profile_steps("") is None
    assert parse_profile_steps(None) is None
    with pytest.raises(ValueError):
        parse_profile_steps("105:100")


def test_profile_window_anchoring(tmp_path):
    # absolute window: starts exactly at the named step, any start_step
    ctl = ProfileController(
        steps_spec="100:105", output_dir=str(tmp_path), start_step=90
    )
    assert ctl.window == (100, 105)
    assert ctl.profile_dir == os.path.join(str(tmp_path), "obs", "profile")
    # legacy count: relative to the run's start, skipping the compile step
    ctl = ProfileController(
        steps_spec=3, profile_dir=str(tmp_path / "d"), start_step=10,
        output_dir=str(tmp_path),
    )
    assert ctl.window == (12, 14)


@pytest.mark.slow  # ~13s: jax's profiler session init dominates; the
# cheap window/spec logic above keeps fast-tier coverage of the controller
def test_profile_trigger_capture(tmp_path, capsys):
    trigger = str(tmp_path / "profile.trigger")
    ctl = ProfileController(
        steps_spec=0,
        trigger_path=trigger,
        output_dir=str(tmp_path),
        start_step=0,
    )
    ctl.before_step(5)
    assert not ctl.active  # no trigger yet
    with open(trigger, "w") as f:
        f.write("2")
    ctl.before_step(5)
    assert ctl.active
    assert not os.path.exists(trigger)  # consumed
    ctl.after_step(5)
    assert ctl.active  # window is 2 steps
    ctl.after_step(6)
    assert not ctl.active
    # the capture dir is self-describing: proc index + step window +
    # wall clock, so report/devprof locate THIS capture without globbing
    base = os.path.join(str(tmp_path), "obs", "profile")
    dirs = [d for d in os.listdir(base) if d.startswith("proc000-s000005-000006-")]
    assert dirs, f"no step-stamped capture dir under {base}: {os.listdir(base)}"
    trace_dir = os.path.join(base, dirs[0])
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(trace_dir) for f in fs]
    assert files, f"no trace files under {trace_dir}"
    lines = _json_lines(capsys.readouterr().out)
    assert any(r.get("event") == "profile_trace" for r in lines)
    captured = next(r for r in lines if r.get("event") == "profile_captured")
    assert captured["path"] == trace_dir
    assert captured["window"] == [5, 6] and captured["steps"] == 2


# ---------------------------------------------------------------------------
# CI/tooling: the repo AST lint's json-emission rule
# ---------------------------------------------------------------------------

def test_repo_lint_forbids_rogue_json_print(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    repo_lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(repo_lint)

    rogue = tmp_path / "rogue.py"
    rogue.write_text(
        "import json\n"
        "print(json.dumps({'step': 1, 'loss': 0.5}))\n"
        "print('plain text is fine')\n"
    )
    rel = os.path.join("distributed_llms_example_tpu", "train", "rogue.py")
    violations = repo_lint.lint_file(str(rogue), rel)
    assert len(violations) == 1 and "sink" in violations[0]
    # the sink layer itself is allowed
    rel = os.path.join("distributed_llms_example_tpu", "obs", "sink.py")
    assert repo_lint.lint_file(str(rogue), rel) == []
    rel = os.path.join("distributed_llms_example_tpu", "utils", "jsonlog.py")
    assert repo_lint.lint_file(str(rogue), rel) == []
    # and the repo itself stays clean under the new rule
    assert repo_lint.main([]) == 0


@pytest.mark.slow  # two AOT gauge compiles: slow tier
def test_mfu_flops_invariant_under_grad_accum():
    """The MFU numerator is ×N-corrected under grad accumulation: XLA's
    cost analysis counts the scan's while body exactly ONCE (measured on
    jax 0.4.37 — without the correction MFU would underreport by ~N), so
    gauges.py scales by grad_accum_steps.  At the same effective batch
    the corrected flops match accum=1 from below (equal model flops) and
    exceed it only by N-1 extra optimizer tails + loop bookkeeping —
    ~10% at this toy width, ~0 at real widths.  grad_accum_steps is
    stamped into the gauge report."""
    from distributed_llms_example_tpu.obs.gauges import train_step_static_gauges

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    reports = {}
    for n in (1, 4):
        reports[n] = train_step_static_gauges(
            "t5-test", mesh, global_batch=16, src_len=32, tgt_len=16,
            dtype="bfloat16", grad_accum_steps=n,
        )
    assert reports[1]["grad_accum_steps"] == 1
    assert reports[4]["grad_accum_steps"] == 4
    assert reports[1]["flops_source"] == reports[4]["flops_source"] == "hlo_cost_analysis"
    f1, f4 = reports[1]["flops_per_step"], reports[4]["flops_per_step"]
    assert f1 > 0
    # same effective batch → same model flops, so the ×N-corrected accum
    # count brackets accum=1: at least f1 (nothing lost — an uncorrected
    # body-counted-once number would sit at ~f1/4), at most f1 + the
    # (N-1) duplicated optimizer tails (~10% at this toy width)
    assert f1 * 0.98 <= f4 <= f1 * 1.2
