"""Train-step tests on the 8-device mesh: loss decreases, grad accumulation
is exact, schedules and decay masks behave."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.parallel.sharding import shard_params
from distributed_llms_example_tpu.train.optim import (
    decay_mask,
    linear_schedule_with_warmup,
    make_optimizer,
)
from distributed_llms_example_tpu.train.step import (
    create_train_state,
    make_train_step,
    put_batch,
    state_shardings,
)


def _toy_batch(b=8, src=16, tgt=8, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
    attn = np.ones((b, src), np.int32)
    labels = rng.randint(2, vocab, (b, tgt)).astype(np.int32)
    labels[:, -2:] = LABEL_PAD
    return {"input_ids": input_ids, "attention_mask": attn, "labels": labels}


@pytest.fixture(scope="module")
def setup(request):
    lm = load_model("t5-test")
    # keep fixture params on host: device_put can alias CPU buffers, and a
    # donating train step would delete them out from under later tests
    params = jax.device_get(lm.init_params(0))
    return lm, params


def test_loss_decreases(mesh8, setup):
    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=1000)
    build = make_train_step(lm.module, lm.config, tx, schedule, mesh8)
    params = shard_params(params, mesh8)
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    batch = put_batch(_toy_batch(), mesh8)
    losses = []
    for _ in range(12):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert int(jax.device_get(state.step)) == 12
    assert float(metrics["target_tokens"]) == 8 * 6  # 2 label cols masked


def test_grad_accum_matches_full_batch(mesh8, setup):
    """grad_accum=4 over a batch must produce the same updated params as a
    single full-batch step (token-weighted accumulation is exact).

    Uses SGD so the param delta IS the accumulated gradient — Adam's
    g/(|g|+eps) at step 1 amplifies fp summation-order noise for
    near-zero gradient entries and would hide real errors behind a loose
    tolerance.
    """
    import optax

    lm, params = setup
    tx = optax.sgd(1e-2)
    schedule = lambda step: 1e-2  # noqa: E731
    batch = _toy_batch(b=8)
    # vary the mask so microbatches have different token counts
    batch["labels"][0:2, 3:] = LABEL_PAD

    outs = []
    for accum in (1, 4):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, grad_accum_steps=accum, donate=False
        )
        state = create_train_state(shard_params(params, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        new_state, metrics = step(state, put_batch(batch, mesh8))
        outs.append((jax.device_get(new_state.params), float(metrics["loss"])))
    p1, l1 = outs[0]
    p4, l4 = outs[1]
    assert abs(l1 - l4) < 1e-5
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_sharded_step_equals_single_device(mesh8, setup):
    """A tensor=2/fsdp=2/data=2 train step must produce the same loss,
    grad-norm, and updated params as the identical step on a 1-device mesh
    — the test that catches wrong sharding rules (a bad spec changes
    numerics through mis-reduced collectives, not just performance)."""
    import optax

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh

    lm, params = setup
    tx = optax.sgd(1e-2)
    schedule = lambda step: 1e-2  # noqa: E731
    batch = _toy_batch(b=8)
    batch["labels"][0:2, 3:] = LABEL_PAD  # uneven token counts across shards

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    outs = {}
    for name, mesh in (("sharded", mesh8), ("single", mesh1)):
        build = make_train_step(lm.module, lm.config, tx, schedule, mesh, donate=False)
        state = create_train_state(shard_params(params, mesh), tx)
        sh = state_shardings(state, mesh)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        new_state, metrics = step(state, put_batch(batch, mesh))
        outs[name] = (
            jax.device_get(new_state.params),
            float(metrics["loss"]),
            float(metrics["grad_norm"]),
        )
    p_sh, loss_sh, gn_sh = outs["sharded"]
    p_1, loss_1, gn_1 = outs["single"]
    assert loss_sh == pytest.approx(loss_1, rel=1e-5)
    assert gn_sh == pytest.approx(gn_1, rel=1e-4)
    for a, b in zip(jax.tree.leaves(p_sh), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5)


def test_schedule_shape():
    s = linear_schedule_with_warmup(1e-4, warmup_steps=10, total_steps=110)
    assert float(s(0)) == 0.0
    assert float(s(10)) == pytest.approx(1e-4, rel=1e-6)  # fp32 schedule values
    assert float(s(60)) == pytest.approx(5e-5, rel=1e-3)
    assert float(s(110)) == pytest.approx(0.0, abs=1e-10)


def test_decay_mask(setup):
    lm, params = setup
    mask = decay_mask(params)
    assert mask["shared"]["embedding"] is True
    blk = mask["encoder"]["block_0"]
    assert blk["self_attn"]["q_proj"]["kernel"] is True
    assert blk["self_attn_norm"]["scale"] is False


def test_state_shardings_cover_opt_state(mesh8, setup):
    lm, params = setup
    tx, _ = make_optimizer()
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh8)
    # adam moments of q_proj kernels must be sharded like the kernel itself
    flat = jax.tree_util.tree_leaves_with_path(sh)
    qproj = [s for path, s in flat if "q_proj" in str(path)]
    assert len(qproj) >= 3  # param + mu + nu
    assert len({str(s) for s in qproj}) == 1


def test_dropout_step_runs(mesh8, setup):
    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    build = make_train_step(lm.module, lm.config, tx, schedule, mesh8, with_dropout=True)
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    state, metrics = step(state, put_batch(_toy_batch(), mesh8), jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # ~15s extra step compile for the rbg key type: slow
# tier (test_dropout_step_runs pins the threefry path fast)
def test_dropout_step_accepts_rbg_key(mesh8, setup):
    """--prng-impl rbg hands the step a TYPED key array (TPU hardware RNG
    stream); the jitted step's replicated rng sharding must accept it and
    grad accumulation's fold_in must work on it."""
    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh8, with_dropout=True, grad_accum_steps=2
    )
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    key = jax.random.key(3, impl="rbg")
    state, metrics = step(state, put_batch(_toy_batch(), mesh8), key)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # ~12s per-policy recompiles: slow tier
def test_remat_policies_match_no_remat(mesh8):
    """Remat never changes math — 'full' and 'dots' policies must produce
    the identical loss as no remat at all."""
    import optax

    losses = {}
    batch = _toy_batch(b=8)
    for policy in (None, "full", "dots"):
        lm = load_model(
            "llama-test",
            remat=policy is not None,
            remat_policy=policy or "full",
        )
        tx = optax.sgd(1e-2)
        build = make_train_step(
            lm.module, lm.config, tx, lambda s: 1e-2, mesh8, donate=False, is_seq2seq=False
        )
        params = jax.device_get(lm.init_params(0))
        state = create_train_state(shard_params(params, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        cb = {
            "input_ids": batch["input_ids"],
            "attention_mask": batch["attention_mask"],
            "labels": batch["input_ids"],
        }
        _, metrics = step(state, put_batch(cb, mesh8))
        losses[policy] = float(metrics["loss"])
    assert losses["full"] == pytest.approx(losses[None], rel=1e-6)
    assert losses["dots"] == pytest.approx(losses[None], rel=1e-6)


# ---------------------------------------------------------------------------
# In-step gradient accumulation (ISSUE 5): the single-apply contract vs the
# optax.MultiSteps oracle, donation safety, the compiled carry's sharded
# fp32 accumulators, and once-per-optimizer-step health/counting.
# ---------------------------------------------------------------------------


@pytest.mark.slow  # MultiSteps' lax.cond inner-apply compiles (~18s CPU): slow tier
def test_single_apply_bit_equal_vs_multisteps(setup):
    """The accumulation window's optimizer apply is bit-equal to a single
    apply on the full gradient: MultiSteps with use_grad_mean=False sums
    its inputs (g/2 + g/2 == g exactly in binary fp) and runs the inner
    tx exactly once on the window's last microbatch — the cross-check
    oracle for the scan's single-apply contract (train/optim.py
    multisteps_reference).  Both sides go through multisteps_reference
    (k=1 vs k=2) so they share the lax.cond-compiled inner apply — an
    eager op-by-op tx.update sees different XLA fusion (FMA) and differs
    at the ulp level, which is execution mode, not accumulation."""
    import optax

    from distributed_llms_example_tpu.train.optim import multisteps_reference

    lm, params = setup
    tx, _ = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    g = jax.tree.map(lambda p: (p * 0.1 + 0.01).astype(jnp.float32), params)

    ms1 = multisteps_reference(tx, 1)
    updates, _ = ms1.update(g, ms1.init(params), params)
    p_once = optax.apply_updates(params, updates)

    ms = multisteps_reference(tx, 2)
    s = ms.init(params)
    half = jax.tree.map(lambda x: x * 0.5, g)  # exact halving in binary fp
    u1, s = ms.update(half, s, params)
    # mid-window: MultiSteps emits zero updates, no apply happened
    assert all(not np.any(np.asarray(u)) for u in jax.tree.leaves(u1))
    # and the accumulated gradient is the exact sum of the halves
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(s.acc_grads)[0]),
        np.asarray(jax.tree.leaves(half)[0]),
    )
    u2, s = ms.update(half, s, params)
    p_ms = optax.apply_updates(params, u2)
    for a, b in zip(jax.tree.leaves(p_once), jax.tree.leaves(p_ms)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # compiles an accum step + eager grads (~30s CPU): slow tier
def test_grad_accum_step_matches_multisteps_trajectory(mesh8, setup):
    """End-to-end cross-check: the compiled accum=2 AdamW step lands on
    the same params as optax.MultiSteps driven with the per-microbatch
    token-normalized gradients computed eagerly (shard-local grouping:
    microbatch n takes rows n::N).  The scan normalizes the SUM once,
    MultiSteps sums pre-normalized terms — ulp-level gradient
    differences, but AdamW's g/(sqrt(nu)+eps) acts like sign(g) where
    |g| is tiny, so a single ulp flip there can move an update by up to
    2·lr on that element.  Hence two bounds: elementwise 2.5·lr (sign
    flips on isolated near-zero-gradient elements are execution noise),
    and mean |diff| under 5% of lr (a real accumulation bug — a second
    optimizer apply, wrong normalization, a dropped microbatch — moves
    the whole tree by O(lr))."""
    import optax

    from distributed_llms_example_tpu.train.optim import multisteps_reference
    from distributed_llms_example_tpu.train.step import make_loss_fn

    lm, params = setup
    N = 2
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    batch = _toy_batch(b=8)
    batch["labels"][0:2, 3:] = LABEL_PAD  # uneven tokens across microbatches

    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh8, grad_accum_steps=N, donate=False
    )
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    new_state, metrics = step(state, put_batch(batch, mesh8))
    p_step = jax.device_get(new_state.params)

    loss_sums = make_loss_fn(lm.module, lm.config, 0.0, is_seq2seq=True)
    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_sums(p, b), has_aux=True))
    mbs = [{k: v[n::N] for k, v in batch.items()} for n in range(N)]
    sums = [grad_fn(params, mb) for mb in mbs]
    total_tokens = sum(float(tok) for (_, tok), _ in sums)
    assert float(metrics["target_tokens"]) == total_tokens
    lsum_total = sum(float(ls) for (ls, _), _ in sums)
    assert float(metrics["loss"]) == pytest.approx(lsum_total / total_tokens, rel=1e-6)

    ms = multisteps_reference(tx, N)
    s = ms.init(params)
    p_ms = params
    for (_, _), grads in sums:
        gnorm = jax.tree.map(lambda g: (g / total_tokens).astype(jnp.float32), grads)
        u, s = ms.update(gnorm, s, p_ms)
        p_ms = optax.apply_updates(p_ms, u)
    lr = 1e-3
    diffs = [
        np.abs(np.asarray(a) - np.asarray(b))
        for a, b in zip(jax.tree.leaves(p_step), jax.tree.leaves(p_ms))
    ]
    assert max(d.max() for d in diffs) < 2.5 * lr
    total = sum(d.sum() for d in diffs)
    count = sum(d.size for d in diffs)
    assert total / count < 0.05 * lr


@pytest.mark.slow  # two full compiles (donate on/off): slow tier
def test_grad_accum_donation_safe(mesh8, setup):
    """donate=True under accumulation must not reuse a stale buffer: a
    3-step donated trajectory equals the non-donated one exactly (the
    accumulators and carry are donation-internal; the input state is the
    only donated argument, and it is consumed exactly once per step)."""
    import optax

    lm, params = setup
    batch = _toy_batch(b=8)
    trajectories = {}
    for donate in (False, True):
        tx = optax.sgd(1e-2)
        build = make_train_step(
            lm.module, lm.config, tx, lambda s: 1e-2, mesh8,
            grad_accum_steps=2, donate=donate,
        )
        state = create_train_state(shard_params(params, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        gb = put_batch(batch, mesh8)
        losses = []
        for _ in range(3):
            state, metrics = step(state, gb)
            losses.append(float(metrics["loss"]))
        trajectories[donate] = (losses, jax.device_get(state.params))
    l_no, p_no = trajectories[False]
    l_yes, p_yes = trajectories[True]
    assert l_yes == pytest.approx(l_no, rel=1e-6)
    for a, b in zip(jax.tree.leaves(p_no), jax.tree.leaves(p_yes)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6)


def test_grad_accum_validation_and_pipeline_guard(mesh8, setup):
    """Config validation fails loudly: accum < 1 at build, indivisible
    batch at trace, and a stage>1 pipeline adapter (which owns its own
    microbatching) at build with the composition table's message."""
    import optax

    from distributed_llms_example_tpu.analysis.composition import reason_for

    lm, params = setup
    tx = optax.sgd(1e-2)
    sched = lambda s: 1e-2  # noqa: E731
    with pytest.raises(ValueError, match="grad_accum_steps"):
        make_train_step(lm.module, lm.config, tx, sched, mesh8, grad_accum_steps=0)

    class _FakePipe:
        num_microbatches = 4

    with pytest.raises(ValueError) as ei:
        make_train_step(_FakePipe(), lm.config, tx, sched, mesh8, grad_accum_steps=2)
    assert str(ei.value) == reason_for("grad-accum-pipelined")

    build = make_train_step(
        lm.module, lm.config, tx, sched, mesh8, grad_accum_steps=3, donate=False
    )
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    with pytest.raises(ValueError, match="not divisible"):
        step(state, put_batch(_toy_batch(b=8), mesh8))


@pytest.mark.slow  # its own health-step compile: slow tier
def test_grad_accum_health_once_per_optimizer_step(mesh8, setup):
    """health=True at accum>1 emits ONE metrics bundle per optimizer step
    (the watchdog's cadence unit): every health key present exactly once,
    the step counter advances by one per global batch, and the schedule is
    read at the optimizer step — microbatches are invisible."""
    from distributed_llms_example_tpu.train.step import HEALTH_METRIC_KEYS

    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh8,
        grad_accum_steps=4, health=True, donate=False,
    )
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    gb = put_batch(_toy_batch(), mesh8)
    state, metrics = step(state, gb)
    for k in HEALTH_METRIC_KEYS:
        assert k in metrics, k
    assert float(metrics["learning_rate"]) == pytest.approx(float(schedule(0)))
    assert int(jax.device_get(state.step)) == 1  # one optimizer step, not 4
    state, metrics = step(state, gb)
    assert int(jax.device_get(state.step)) == 2
    assert float(metrics["nonfinite_count"]) == 0.0


@pytest.mark.slow  # an AOT fsdp=8 compile + HLO text scan: slow tier
def test_grad_accum_carry_sharded_and_optimizer_outside_scan(setup):
    """The two compiled-program contracts, pinned on a pure-FSDP step:

    1. the scan carry's fp32 accumulators keep the param sharding — no
       while-loop carry element has the FULL global shape of any sharded
       param (a replicated accumulator would put a param-sized fp32 leaf
       in the carry on every device);
    2. the optimizer/clip/health block appears in the program (census
       total > 0) and NO instruction of it sits inside a loop body —
       clip + AdamW run once per optimizer step, after the scan
       (analysis/ir_lint.py once_per_step_placement over the source-span
       metadata of train/step.py optimizer_apply_block).
    """
    import re

    from distributed_llms_example_tpu.analysis.ir_lint import (
        once_per_step_finding,
        once_per_step_placement,
    )
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.train.step import once_per_step_source_spans

    lm, params = setup
    mesh = build_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh, grad_accum_steps=2, donate=False
    )
    state = create_train_state(shard_params(params, mesh), tx)
    sh = state_shardings(state, mesh)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    batch = _toy_batch(b=16)  # microbatch 8 rows over 8 fsdp shards
    compiled = step.jitted.lower(state, put_batch(batch, mesh)).compile()
    text = compiled.as_text()

    # -- contract 2: the once-per-step census --------------------------------
    spans = once_per_step_source_spans()
    census = once_per_step_placement(text, spans)
    assert census["total"] > 0, "optimizer block's source spans missing from HLO"
    assert census["in_loop"] == 0, census
    assert once_per_step_finding(text, spans) is None

    # -- contract 1: the scan carry never holds a full-size f32 leaf ---------
    # The program has OTHER while loops on CPU (XLA lowers the embedding
    # backward's scatter-add to a while, and those legitimately carry
    # full-size operands) — the accumulation scan is the one whose carry
    # holds the two f32[] scalars (loss sum, token sum) next to the fp32
    # gradient accumulators.
    carries = re.findall(r"=\s*\(([^)]*)\)\s+while\(", text)
    assert carries, "no while loop found — the accumulation scan is gone"
    scan_carries = [c for c in carries if len(re.findall(r"f32\[\]", c)) >= 2]
    assert len(scan_carries) == 1, (
        f"expected exactly one accumulation-scan while (2 f32[] scalars in "
        f"the carry), found {len(scan_carries)} of {len(carries)}"
    )
    # The carry also legitimately holds FULL-size f32 weights: XLA hoists
    # the all-gathered fsdp params through the while as loop invariants
    # (gather once, use N times).  So "no full shape present" is the wrong
    # predicate — instead count: every shard shape must appear at least as
    # many times as there are param leaves with that shard shape.  A
    # replicated accumulator swaps its shard-shaped carry slot for a
    # full-shaped one and the count drops below the param count.
    from collections import Counter

    carry_counts = Counter(re.findall(r"f32\[[0-9,]*\]", scan_carries[0]))
    shard_counts = Counter()
    n_sharded = 0
    for p_leaf, s_leaf in zip(jax.tree.leaves(state.params), jax.tree.leaves(sh.params)):
        global_shape = tuple(p_leaf.shape)
        shard_shape = s_leaf.shard_shape(global_shape)
        shard_counts["f32[" + ",".join(str(d) for d in shard_shape) + "]"] += 1
        if shard_shape != global_shape:
            n_sharded += 1
    assert n_sharded, "no param is sharded — the fixture mesh is broken"
    for shape, need in shard_counts.items():
        assert carry_counts[shape] >= need, (
            f"scan carry holds {carry_counts[shape]} x {shape} but the param "
            f"tree has {need} leaves with that shard shape — an accumulator "
            f"lost its param sharding (replicated into the carry full-size)"
        )
