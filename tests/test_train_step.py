"""Train-step tests on the 8-device mesh: loss decreases, grad accumulation
is exact, schedules and decay masks behave."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.parallel.sharding import shard_params
from distributed_llms_example_tpu.train.optim import (
    decay_mask,
    linear_schedule_with_warmup,
    make_optimizer,
)
from distributed_llms_example_tpu.train.step import (
    create_train_state,
    make_train_step,
    put_batch,
    state_shardings,
)


def _toy_batch(b=8, src=16, tgt=8, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
    attn = np.ones((b, src), np.int32)
    labels = rng.randint(2, vocab, (b, tgt)).astype(np.int32)
    labels[:, -2:] = LABEL_PAD
    return {"input_ids": input_ids, "attention_mask": attn, "labels": labels}


@pytest.fixture(scope="module")
def setup(request):
    lm = load_model("t5-test")
    # keep fixture params on host: device_put can alias CPU buffers, and a
    # donating train step would delete them out from under later tests
    params = jax.device_get(lm.init_params(0))
    return lm, params


def test_loss_decreases(mesh8, setup):
    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=1000)
    build = make_train_step(lm.module, lm.config, tx, schedule, mesh8)
    params = shard_params(params, mesh8)
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    batch = put_batch(_toy_batch(), mesh8)
    losses = []
    for _ in range(12):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert int(jax.device_get(state.step)) == 12
    assert float(metrics["target_tokens"]) == 8 * 6  # 2 label cols masked


def test_grad_accum_matches_full_batch(mesh8, setup):
    """grad_accum=4 over a batch must produce the same updated params as a
    single full-batch step (token-weighted accumulation is exact).

    Uses SGD so the param delta IS the accumulated gradient — Adam's
    g/(|g|+eps) at step 1 amplifies fp summation-order noise for
    near-zero gradient entries and would hide real errors behind a loose
    tolerance.
    """
    import optax

    lm, params = setup
    tx = optax.sgd(1e-2)
    schedule = lambda step: 1e-2  # noqa: E731
    batch = _toy_batch(b=8)
    # vary the mask so microbatches have different token counts
    batch["labels"][0:2, 3:] = LABEL_PAD

    outs = []
    for accum in (1, 4):
        build = make_train_step(
            lm.module, lm.config, tx, schedule, mesh8, grad_accum_steps=accum, donate=False
        )
        state = create_train_state(shard_params(params, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        new_state, metrics = step(state, put_batch(batch, mesh8))
        outs.append((jax.device_get(new_state.params), float(metrics["loss"])))
    p1, l1 = outs[0]
    p4, l4 = outs[1]
    assert abs(l1 - l4) < 1e-5
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_sharded_step_equals_single_device(mesh8, setup):
    """A tensor=2/fsdp=2/data=2 train step must produce the same loss,
    grad-norm, and updated params as the identical step on a 1-device mesh
    — the test that catches wrong sharding rules (a bad spec changes
    numerics through mis-reduced collectives, not just performance)."""
    import optax

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh

    lm, params = setup
    tx = optax.sgd(1e-2)
    schedule = lambda step: 1e-2  # noqa: E731
    batch = _toy_batch(b=8)
    batch["labels"][0:2, 3:] = LABEL_PAD  # uneven token counts across shards

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    outs = {}
    for name, mesh in (("sharded", mesh8), ("single", mesh1)):
        build = make_train_step(lm.module, lm.config, tx, schedule, mesh, donate=False)
        state = create_train_state(shard_params(params, mesh), tx)
        sh = state_shardings(state, mesh)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        new_state, metrics = step(state, put_batch(batch, mesh))
        outs[name] = (
            jax.device_get(new_state.params),
            float(metrics["loss"]),
            float(metrics["grad_norm"]),
        )
    p_sh, loss_sh, gn_sh = outs["sharded"]
    p_1, loss_1, gn_1 = outs["single"]
    assert loss_sh == pytest.approx(loss_1, rel=1e-5)
    assert gn_sh == pytest.approx(gn_1, rel=1e-4)
    for a, b in zip(jax.tree.leaves(p_sh), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5)


def test_schedule_shape():
    s = linear_schedule_with_warmup(1e-4, warmup_steps=10, total_steps=110)
    assert float(s(0)) == 0.0
    assert float(s(10)) == pytest.approx(1e-4, rel=1e-6)  # fp32 schedule values
    assert float(s(60)) == pytest.approx(5e-5, rel=1e-3)
    assert float(s(110)) == pytest.approx(0.0, abs=1e-10)


def test_decay_mask(setup):
    lm, params = setup
    mask = decay_mask(params)
    assert mask["shared"]["embedding"] is True
    blk = mask["encoder"]["block_0"]
    assert blk["self_attn"]["q_proj"]["kernel"] is True
    assert blk["self_attn_norm"]["scale"] is False


def test_state_shardings_cover_opt_state(mesh8, setup):
    lm, params = setup
    tx, _ = make_optimizer()
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh8)
    # adam moments of q_proj kernels must be sharded like the kernel itself
    flat = jax.tree_util.tree_leaves_with_path(sh)
    qproj = [s for path, s in flat if "q_proj" in str(path)]
    assert len(qproj) >= 3  # param + mu + nu
    assert len({str(s) for s in qproj}) == 1


def test_dropout_step_runs(mesh8, setup):
    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    build = make_train_step(lm.module, lm.config, tx, schedule, mesh8, with_dropout=True)
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    state, metrics = step(state, put_batch(_toy_batch(), mesh8), jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["loss"]))


def test_dropout_step_accepts_rbg_key(mesh8, setup):
    """--prng-impl rbg hands the step a TYPED key array (TPU hardware RNG
    stream); the jitted step's replicated rng sharding must accept it and
    grad accumulation's fold_in must work on it."""
    lm, params = setup
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh8, with_dropout=True, grad_accum_steps=2
    )
    state = create_train_state(shard_params(params, mesh8), tx)
    sh = state_shardings(state, mesh8)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    key = jax.random.key(3, impl="rbg")
    state, metrics = step(state, put_batch(_toy_batch(), mesh8), key)
    assert np.isfinite(float(metrics["loss"]))


def test_remat_policies_match_no_remat(mesh8):
    """Remat never changes math — 'full' and 'dots' policies must produce
    the identical loss as no remat at all."""
    import optax

    losses = {}
    batch = _toy_batch(b=8)
    for policy in (None, "full", "dots"):
        lm = load_model(
            "llama-test",
            remat=policy is not None,
            remat_policy=policy or "full",
        )
        tx = optax.sgd(1e-2)
        build = make_train_step(
            lm.module, lm.config, tx, lambda s: 1e-2, mesh8, donate=False, is_seq2seq=False
        )
        params = jax.device_get(lm.init_params(0))
        state = create_train_state(shard_params(params, mesh8), tx)
        sh = state_shardings(state, mesh8)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        step, _ = build(state)
        cb = {
            "input_ids": batch["input_ids"],
            "attention_mask": batch["attention_mask"],
            "labels": batch["input_ids"],
        }
        _, metrics = step(state, put_batch(cb, mesh8))
        losses[policy] = float(metrics["loss"])
    assert losses["full"] == pytest.approx(losses[None], rel=1e-6)
    assert losses["dots"] == pytest.approx(losses[None], rel=1e-6)
