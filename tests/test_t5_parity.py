"""Numerical parity of our T5 against HF PyTorch T5.

No network: an HF torch T5 is constructed with random init from an in-code
config, its state_dict converted with our converter, and forward logits
compared.  This validates the model math and the converter at once.
"""

import numpy as np
import pytest

from distributed_llms_example_tpu.models.convert import convert_t5_state_dict
from distributed_llms_example_tpu.models.t5 import T5Config, T5ForConditionalGeneration, shift_right

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _make_pair(gated: bool = False):
    hf_cfg = transformers.T5Config(
        vocab_size=128,
        d_model=64,
        d_kv=16,
        d_ff=96,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=32,
        dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=not gated,
        decoder_start_token_id=0,
    )
    torch.manual_seed(0)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = T5Config(
        vocab_size=128,
        d_model=64,
        d_kv=16,
        d_ff=96,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=32,
        dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=not gated,
    )
    model = T5ForConditionalGeneration(cfg)
    params = convert_t5_state_dict(hf_model.state_dict())
    return hf_model, model, params


def _batch(seed=0, b=2, src=12, tgt=7, vocab=128):
    rng = np.random.RandomState(seed)
    input_ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
    attn = np.ones((b, src), np.int32)
    attn[0, -3:] = 0  # padding on one row to exercise masking
    dec_ids = rng.randint(2, vocab, (b, tgt)).astype(np.int32)
    return input_ids, attn, dec_ids


@pytest.mark.parametrize("gated", [False, True], ids=["t5v1-relu-tied", "t5v11-gated-untied"])
def test_forward_parity(gated):
    hf_model, model, params = _make_pair(gated)
    input_ids, attn, dec_ids = _batch()
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.tensor(input_ids, dtype=torch.long),
            attention_mask=torch.tensor(attn, dtype=torch.long),
            decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long),
        ).logits.numpy()
    got = model.apply({"params": params}, input_ids, attn, dec_ids)
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-4, rtol=2e-3)


def test_shift_right():
    labels = np.array([[5, 6, 7, -100], [8, 9, -100, -100]], np.int32)
    out = shift_right(labels, decoder_start_token_id=0, pad_token_id=0)
    np.testing.assert_array_equal(out, [[0, 5, 6, 7], [0, 8, 9, 0]])


def test_cached_decode_matches_full_forward():
    """Incremental decoding with the KV cache must produce the same logits
    as a full teacher-forced forward pass."""
    import jax
    import jax.numpy as jnp

    _, model, params = _make_pair(False)
    input_ids, attn, dec_ids = _batch()
    full = model.apply({"params": params}, input_ids, attn, dec_ids)

    enc = model.apply({"params": params}, jnp.asarray(input_ids), jnp.asarray(attn), method="encode")
    max_len = dec_ids.shape[1]
    # init full-length cache buffers
    init_vars = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(dec_ids),
        enc,
        jnp.asarray(attn),
        use_cache=True,
        max_kv_len=max_len,
        method="decode",
    )
    cache = init_vars["cache"]
    step_logits = []
    for t in range(max_len):
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            jnp.asarray(dec_ids[:, t : t + 1]),
            enc,
            jnp.asarray(attn),
            use_cache=True,
            cache_offset=t,
            max_kv_len=max_len,
            method="decode",
            mutable=["cache"],
        )
        cache = mut["cache"]
        step_logits.append(np.asarray(logits[:, 0]))
    stepwise = np.stack(step_logits, axis=1)
    np.testing.assert_allclose(stepwise, np.asarray(full), atol=2e-4, rtol=2e-3)
