"""Speculative multi-token decode (ISSUE 20): draft-then-verify.

Acceptance pins: speculative greedy output BIT-identical to plain greedy
through the engine — flat, paged+int8, warm prefix-cache multi-turn, and
the registry draft-model path — with ``accepted_tokens_per_step > 1.0``
when the drafter predicts; an adversarial-draft request storm leaves the
paged pool's free list byte-exact and the prefix-cache hash index free
of speculative entries; the router replica-kill leg stays bit-identical
with speculation on and aggregates the acceptance ledger; the new
``serve_window``/``serve_summary``/``router_summary`` fields round-trip
through ``obs.report``'s loader into the '## Speculative decode' section
and the strict ``--min-acceptance-rate`` gate (missing measurement is
never a pass); repo_lint rule 17 fences acceptance math to
``serving/spec.py`` + ``serving/cache_pool.py``; and ``bench_diff``
knows the new leaves' directions."""

from __future__ import annotations

import importlib.util
import os

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.chaos import parse_chaos
from distributed_llms_example_tpu.obs.report import build_report, render_markdown
from distributed_llms_example_tpu.serving import cache_pool
from distributed_llms_example_tpu.serving import spec as spec_mod
from distributed_llms_example_tpu.serving.engine import (
    ServeConfig,
    ServingEngine,
    trim_eos,
)
from distributed_llms_example_tpu.serving.router import (
    ReplicaRouter,
    RouterConfig,
)


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


# ------------------------------------------------------------ pure drafting


def test_ngram_draft_repetition_and_fallback():
    """The self-drafter is a longest-suffix n-gram lookup: on a repeating
    stream it proposes the continuation of the most recent earlier
    occurrence; with no repetition it falls back to repeating the last
    token; it always returns exactly k proposals."""
    # period-3 loop: the suffix trigram recurs, the draft continues it
    h = [5, 6, 7, 5, 6, 7, 5, 6]
    assert spec_mod.ngram_draft(h, 4) == [7, 5, 6, 7]
    # no repetition at all → last-token fallback
    assert spec_mod.ngram_draft([1, 2, 3], 3) == [3, 3, 3]
    assert spec_mod.ngram_draft([], 2) == [0, 0]
    # the match running off the end continues the periodic fill
    assert spec_mod.ngram_draft([9, 9], 4) == [9, 9, 9, 9]
    # most RECENT prior occurrence wins (suffix [2] matches twice; the
    # later match at index 3 is followed by 8, the earlier one by 7)
    assert spec_mod.ngram_draft([2, 7, 0, 2, 8, 1, 2], 1) == [8]
    for k in (1, 3, 7):
        assert len(spec_mod.ngram_draft([4, 5], k)) == k


def test_ngram_drafts_batched_pads_idle():
    out = spec_mod.ngram_drafts([[5, 6, 5], None, []], 3, pad=0)
    assert out.shape == (3, 3) and out.dtype == np.int32
    # unigram match at index 0: continuation [6, 5], then period-1 fill
    assert out[0].tolist() == [6, 5, 5]
    assert out[1].tolist() == [0, 0, 0]
    assert out[2].tolist() == [0, 0, 0]


def test_acceptance_lengths_rule_and_room_clamp():
    """The acceptance rule verbatim: cumprod of draft==target prefix
    matches, clamped to the slot's remaining budget room — the clamp
    truncates acceptance, it never changes which tokens match."""
    # x rows: [last, d1, d2, d3]; target rows: argmax at each position
    x = jnp.asarray([
        [10, 7, 8, 9],   # drafts all match → accept 3
        [10, 7, 8, 9],   # d1 matches, d2 wrong → accept 1
        [10, 5, 8, 9],   # d1 wrong (even though d2 'matches') → accept 0
        [10, 7, 8, 9],   # all match but room clamps at 2
    ], jnp.int32)
    target = jnp.asarray([
        [7, 8, 9, 1],
        [7, 2, 9, 1],
        [7, 8, 9, 1],
        [7, 8, 9, 1],
    ], jnp.int32)
    room = jnp.asarray([3, 3, 3, 2], jnp.int32)
    got = np.asarray(spec_mod.acceptance_lengths(x, target, room))
    assert got.tolist() == [3, 1, 0, 2]


# ------------------------------------------------------- engine bit-identity


def _requests(rng, n=8, lo=3, hi=14, vocab=120):
    return [list(rng.randint(4, vocab, rng.randint(lo, hi))) for _ in range(n)]


def _engine(lm, *, W=16, L=8, slots=2, **kw):
    return ServingEngine(
        lm.module, lm.config, None,
        ServeConfig(
            max_slots=slots, prefill_batch=slots, max_new_tokens=L,
            max_source_length=W, log_every_steps=0, request_spans=False, **kw,
        ),
        is_seq2seq=False,
    )


@pytest.fixture(scope="module")
def llama_spec():
    """One plain flat-f32 greedy run: the oracle every speculative
    configuration must reproduce bit-for-bit."""
    lm = load_model("llama-test")
    params = lm.init_params(0)
    rng = np.random.RandomState(7)
    reqs = _requests(rng)
    plain = _engine(lm).generate(params, reqs)
    return lm, params, reqs, plain


def test_engine_spec_flat_bit_identical_and_ledger(llama_spec):
    """THE acceptance pin: n-gram speculative decode on the flat cache
    emits plain greedy's exact tokens (slot reuse included — 8 requests
    over 2 slots), the ledger adds up (emitted == the tokens decoded,
    per-slot accepted_tokens_per_step >= 1 by construction), and a
    second session retraces nothing."""
    lm, params, reqs, plain = llama_spec
    eng = _engine(lm, spec_tokens=3)
    outs = eng.generate(params, reqs)
    assert outs == plain
    st = eng.last_stats
    # the first token of each output is prefill's; the rest are decode's
    assert st.spec_emitted == st.decode_tokens
    assert st.decode_tokens == sum(len(o) for o in outs) - len(reqs)
    assert st.spec_steps > 0 and st.spec_slot_rounds >= st.spec_steps
    assert st.spec_drafted == 3 * st.spec_slot_rounds
    # every emitted token beyond one-per-slot-round is an accepted draft
    assert st.spec_emitted >= st.spec_slot_rounds
    assert 0 <= st.spec_accepted <= st.spec_drafted
    traces = dict(eng.trace_counts)
    assert traces["spec_verify"] == 1
    assert eng.generate(params, reqs) == plain
    assert eng.trace_counts == traces  # zero-recompile churn


def test_engine_spec_paged_int8_bit_identical(llama_spec):
    """Composition: speculation over the paged pool with int8 KV matches
    the NON-speculative paged int8 engine token-for-token (same kernel
    path, same dequant — the argmax expression never forks), and the
    pool drains to zero."""
    lm, params, reqs, _ = llama_spec
    kw = dict(paged_kv=True, kv_block_size=8, kv_cache_dtype="int8")
    want = _engine(lm, **kw).generate(params, reqs)
    eng = _engine(lm, spec_tokens=3, **kw)
    assert eng.generate(params, reqs) == want
    assert eng.pool.blocks_in_use == 0


def test_engine_spec_warm_prefix_multi_turn_bit_identical(llama_spec):
    """Speculation composes with warm prefix-cache hits: shared-prefix
    multi-turn traffic through spec + prefix-cache reproduces the plain
    flat engine's tokens, still HITS the cache, and the hash index holds
    only prompt-chain hashes — never a speculative block."""
    lm, params, _, _ = llama_spec
    rng = np.random.RandomState(23)
    sys_toks = [int(t) for t in rng.randint(4, 120, 8)]
    reqs = [
        sys_toks + [int(t) for t in rng.randint(4, 120, rng.randint(2, 8))]
        for _ in range(8)
    ]
    plain = _engine(lm).generate(params, reqs)
    eng = _engine(
        lm, spec_tokens=3,
        paged_kv=True, kv_block_size=8, pool_blocks=24,
        prefix_cache=True, prefix_cache_budget_gib=0.25,
    )
    outs = eng.generate(params, reqs)
    assert outs == plain
    st = eng.last_stats
    assert st.prefix_hits == len(reqs) - 1  # the shared system block
    assert eng.pool.blocks_in_use == 0
    prompt_hashes = set()
    for r in reqs:
        prompt_hashes.update(cache_pool.chain_hashes(r[:16], 8))
    assert set(eng.pool._index) <= prompt_hashes


def test_engine_spec_draft_model_bit_identical_and_multi_token(llama_spec):
    """The registry draft-model path: with the draft sharing the
    target's weights its proposals ARE the target argmax, so acceptance
    is near-total and the per-slot multi-token rate clears 1.0 by a wide
    margin — while output stays bit-identical to plain greedy (the rule
    accepts nothing greedy would not have emitted)."""
    lm, params, _, _ = llama_spec
    rng = np.random.RandomState(11)
    reqs = _requests(rng, n=6)
    L = 16  # long budgets: room-clamps would mask the acceptance signal
    plain = _engine(lm, L=L).generate(params, reqs)
    eng = _engine(
        lm, L=L, spec_tokens=3, spec_draft_model="llama-test",
        paged_kv=True, kv_block_size=8,
    )
    outs = eng.generate(params, reqs)
    assert outs == plain
    st = eng.last_stats
    atps = st.spec_emitted / max(st.spec_slot_rounds, 1)
    assert atps > 1.0
    assert st.spec_accepted / max(st.spec_drafted, 1) > 0.5
    assert eng.pool.blocks_in_use == 0


def test_engine_spec_validates_composition():
    """Config fencing: seq2seq targets, out-of-range k, and seq2seq
    draft models are rejected at construction — not at decode time."""
    t5 = load_model("t5-test", load_weights=False)
    with pytest.raises(ValueError, match="causal decode"):
        ServingEngine(
            t5.module, t5.config, None,
            ServeConfig(max_slots=2, prefill_batch=2, spec_tokens=2),
            is_seq2seq=True,
        )
    lm = load_model("llama-test", load_weights=False)
    with pytest.raises(ValueError, match="spec_tokens=8"):
        _engine(lm, spec_tokens=8)
    with pytest.raises(ValueError, match="seq2seq"):
        _engine(lm, spec_tokens=2, spec_draft_model="t5-test")


# ------------------------------------------------------- rollback hygiene


def test_spec_pool_storm_adversarial_drafts_no_leak(llama_spec, monkeypatch):
    """The rollback pin: a request storm whose drafts are FORCED wrong
    (adversarial n-gram monkeypatch → every round rejects) leaves the
    paged pool byte-exact — every block back on the free list, refcount
    invariants clean, and not one speculative entry in the prefix-cache
    hash index — while output still matches plain greedy (a wrong draft
    costs throughput, never correctness)."""
    lm, params, _, plain_unused = llama_spec
    rng = np.random.RandomState(31)
    reqs = _requests(rng, n=12)
    plain = _engine(lm).generate(params, reqs)

    def adversarial(histories, k, pad):
        # propose tokens the target essentially never argmaxes (id 3 is
        # outside the 4..120 prompt range) — rejection every round
        return np.full((len(histories), k), 3, np.int32)

    monkeypatch.setattr(spec_mod, "ngram_drafts", adversarial)
    eng = _engine(
        lm, spec_tokens=3,
        paged_kv=True, kv_block_size=8, pool_blocks=24,
        prefix_cache=True, prefix_cache_budget_gib=0.25,
    )
    pre_total = eng.pool.blocks_free
    outs = eng.generate(params, reqs)
    assert outs == plain
    st = eng.last_stats
    assert st.spec_accepted == 0  # the storm really was all-reject
    assert st.spec_emitted == st.spec_slot_rounds  # 1 bonus token/round
    assert eng.pool.blocks_in_use == 0
    # blocks_free counts warm blocks (reclaimable on demand): full
    # capacity is back, byte-exact to the pre-storm free list
    assert eng.pool.blocks_free == pre_total
    assert eng.pool.ref_invariant_violations([]) == []
    prompt_hashes = set()
    for r in reqs:
        prompt_hashes.update(cache_pool.chain_hashes(r[:16], 8))
    assert set(eng.pool._index) <= prompt_hashes


# ------------------------------------------------------- router + report


def test_router_replica_kill_spec_bit_identical(llama_spec):
    """Degraded-mode leg: replica_crash mid-run over spec-enabled
    replicas — every request completes bit-identical to the plain
    single-engine oracle, and the router summary aggregates the tier's
    acceptance ledger."""
    lm, params, _, _ = llama_spec
    rng = np.random.RandomState(41)
    reqs = _requests(rng, n=10, lo=3, hi=10)
    oracle = _engine(lm).generate(params, reqs)

    def spec_engine():
        return _engine(
            lm, spec_tokens=3,
            paged_kv=True, kv_block_size=8, pool_blocks=24,
        )

    router = ReplicaRouter(
        [spec_engine(), spec_engine()], params,
        RouterConfig(log_every_ticks=0, chaos=parse_chaos("replica_crash@4")),
    )
    outs = router.serve(reqs)
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want in zip(outs, oracle):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)
    summary = router.last_stats
    assert summary["completed"] == len(reqs) and summary["shed"] == 0
    assert summary["spec_tokens"] == 3
    assert summary["spec_drafted_tokens"] > 0
    assert 0.0 <= summary["acceptance_rate"] <= 1.0
    assert summary["accepted_tokens_per_step"] >= 1.0


def test_spec_report_section_and_gate(llama_spec, tmp_path, capsys):
    """Schema round-trip + the gate cutting both ways: a spec-enabled
    run's serve_window/serve_summary fields load through the report into
    the '## Speculative decode' section; --min-acceptance-rate passes a
    floor the measured rate meets, fails one above it, and fails
    OUTRIGHT on a run with no spec measurement."""
    from distributed_llms_example_tpu.obs.report import main as report_main
    from scripts.obs_gate import main as gate_main

    lm, params, _, _ = llama_spec
    rng = np.random.RandomState(43)
    reqs = _requests(rng, n=6)
    eng = ServingEngine(
        lm.module, lm.config, None,
        ServeConfig(
            max_slots=2, prefill_batch=2, max_new_tokens=16,
            max_source_length=16, log_every_steps=2, request_spans=False,
            spec_tokens=3, spec_draft_model="llama-test",
        ),
        is_seq2seq=False,
    )
    out = tmp_path / "run"
    sink_mod.install_sink(sink_mod.build_sink("jsonl", str(out)))
    eng.generate(params, reqs)
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    report = build_report(str(out))
    sp = report["spec"]
    assert sp is not None and sp["scope"] == "engine"
    st = eng.last_stats
    assert sp["acceptance_rate"] == pytest.approx(
        st.spec_accepted / max(st.spec_drafted, 1), abs=1e-4
    )
    assert sp["accepted_tokens_per_step"] == pytest.approx(
        st.spec_emitted / max(st.spec_slot_rounds, 1), abs=1e-4
    )
    assert sp["drafted_tokens"] == st.spec_drafted
    assert sp["spec_tokens"] == 3 and sp["draft_model"] == "llama-test"
    assert sp["windows"] > 0  # serve_window rows carried the new fields
    md = render_markdown(report)
    assert "## Speculative decode" in md
    assert "accepted tokens per step" in md
    capsys.readouterr()
    rate = sp["acceptance_rate"]
    assert report_main([
        str(out), "--strict", "--json",
        "--min-acceptance-rate", str(max(rate - 0.01, 1e-6)),
    ]) == 0
    assert report_main([
        str(out), "--strict", "--json",
        "--min-acceptance-rate", str(rate + 0.01),
    ]) == 1
    assert gate_main([
        str(out), "--min-dispatch-efficiency", "0",
        "--min-acceptance-rate", str(max(rate - 0.01, 1e-6)),
    ]) == 0
    # a run with NO spec-enabled summary: missing measurement = fail
    cold = tmp_path / "cold"
    sink_mod.install_sink(sink_mod.build_sink("jsonl", str(cold)))
    _engine(lm).generate(params, reqs[:2])
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    assert build_report(str(cold))["spec"] is None
    assert report_main([
        str(cold), "--strict", "--json", "--min-acceptance-rate", "0.1",
    ]) == 1
    capsys.readouterr()


# ------------------------------------------------------- lint + bench_diff


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name,
        os.path.join(os.path.dirname(__file__), "..", "scripts", f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_lint_rule17_fences_acceptance_math(tmp_path):
    """Rule 17: draft-vs-target compares and acceptance cumprods outside
    serving/spec.py + serving/cache_pool.py are violations; the owner
    files stay exempt."""
    repo_lint = _load_script("repo_lint")
    bad = tmp_path / "sneaky.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def accept(draft_toks, target_toks):\n"
        "    hits = draft_toks == target_toks\n"
        "    return jnp.cumprod(hits, axis=1).sum(axis=1)\n"
    )
    rel = "distributed_llms_example_tpu/serving/sneaky.py"
    out = repo_lint.lint_file(str(bad), rel)
    assert len(out) == 2  # the compare AND the cumprod
    assert all("rule 17" in v or "spec" in v.lower() for v in out)
    # the same text is legal in the owning module
    assert repo_lint.lint_file(
        str(bad), "distributed_llms_example_tpu/serving/spec.py"
    ) == []
    # ...and outside serving/ the rule does not apply
    assert repo_lint.lint_file(
        str(bad), "distributed_llms_example_tpu/ops/sneaky.py"
    ) == []


def test_bench_diff_spec_directions():
    """acceptance_rate / accepted_tokens_per_step / vs_plain regress
    DOWNWARD; spec_tokens and spec_draft_model are config, never a
    regression."""
    bench_diff = _load_script("bench_diff")
    old = {
        "acceptance_rate": 0.8, "accepted_tokens_per_step": 2.5,
        "vs_plain": 0.4, "spec_tokens": 3, "spec_draft_model": "ngram",
    }
    new = {
        "acceptance_rate": 0.4, "accepted_tokens_per_step": 1.2,
        "vs_plain": 0.04, "spec_tokens": 5, "spec_draft_model": "llama-test",
    }
    rows = {r["field"]: r for r in bench_diff.compare(old, new)}
    assert rows["acceptance_rate"]["verdict"] == "regressed"
    assert rows["accepted_tokens_per_step"]["verdict"] == "regressed"
    assert rows["vs_plain"]["verdict"] == "regressed"
    # config leaves never regress (the string draft-model leaf is not
    # even compared numerically — absent or info, never a gate)
    assert rows["spec_tokens"]["verdict"] != "regressed"
    if "spec_draft_model" in rows:
        assert rows["spec_draft_model"]["verdict"] != "regressed"
    # improvements in the same leaves never flag
    rows = {r["field"]: r for r in bench_diff.compare(new, old)}
    for k in ("acceptance_rate", "accepted_tokens_per_step", "vs_plain"):
        assert rows[k]["verdict"] != "regressed"


def test_chatbot_requests_budgets_seed_stable():
    """with_budgets=True rides the SAME rng draws: requests and keys are
    bit-identical to the 2-tuple form, and each budget is the scripted
    reply length for that turn."""
    from distributed_llms_example_tpu.serving.loadgen import chatbot_requests

    kw = dict(sessions=3, turns=2, seed=5, reply_len=(2, 6))
    reqs, keys = chatbot_requests(**kw)
    reqs3, keys3, budgets = chatbot_requests(**kw, with_budgets=True)
    assert reqs3 == reqs and keys3 == keys
    assert len(budgets) == len(reqs)
    assert all(2 <= b <= 6 for b in budgets)
    # the budget IS the gap between a session's consecutive prompts
    # minus the next user message — spot-check via regeneration
    again = chatbot_requests(**kw, with_budgets=True)
    assert again == (reqs3, keys3, budgets)
