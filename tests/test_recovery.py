"""Fault-tolerant training (ISSUE 6).

Acceptance pins: the checkpoint-integrity layer (checksum-manifest
sidecars, save retry-with-backoff, verify-before-restore with fallback
to the previous retained step); the chaos grammar and its one-shot
injection semantics; the recovery controller's escalation (rewind →
skip-batch → halt, quarantine by batch-plan position); parse-time config
validation of the rewind prerequisites; the data loader's
transient-retry + malformed-record skip; the chaos e2e runs on the CPU
mesh (``nan_grad@3 --on-anomaly rewind`` finishes with exactly one
rewind + one quarantine and a bit-exact post-rewind trajectory vs a
clean run that skipped the quarantined batch; ``ckpt_corrupt@2`` resumes
from the previous verified step instead of crashing); and the
``obs.report`` recovery timeline with the injected/organic split
``--strict`` gates on.

The 2-process pod-agreed-rewind leg rides the slow tier next to
tests/test_multiprocess.py.
"""

from __future__ import annotations

import argparse
import json
import os

import jax
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import (
    CheckpointConfig,
    MeshConfig,
    TrainConfig,
    add_tpu_args,
    config_from_args,
)
from distributed_llms_example_tpu.io.checkpoint import Checkpointer, abstract_like
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.chaos import (
    ChaosSchedule,
    corrupt_checkpoint,
    parse_chaos,
)
from distributed_llms_example_tpu.obs.report import build_report, render_markdown
from distributed_llms_example_tpu.train.recovery import RecoveryController


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


# ---------------------------------------------------------------------------
# chaos grammar + one-shot injection semantics
# ---------------------------------------------------------------------------

def test_parse_chaos_grammar(capsys):
    s = parse_chaos("nan_grad@120,ckpt_corrupt@2,data_error@300,sigterm@240")
    assert s.armed_at("nan_grad") == [120]
    assert s.armed_at("ckpt_corrupt") == [2]
    assert s.armed_at("data_error") == [300]
    assert s.armed_at("sigterm") == [240]
    assert not parse_chaos("")  # empty = off
    assert not parse_chaos("   ")
    for bad in ("nan_grad", "nan_grad@", "nan_grad@0", "nan_grad@-3",
                "nan_grad@x", "bogus@5", "@5", "nan_grad@5,"):
        with pytest.raises(ValueError, match="kind@tick"):
            parse_chaos(bad)


def test_chaos_take_is_one_shot(capsys):
    s = parse_chaos("nan_grad@3,nan_grad@7")
    assert not s.take("nan_grad", 2)      # wrong tick
    assert not s.take("ckpt_corrupt", 3)  # wrong kind
    assert s.take("nan_grad", 3)          # fires exactly once...
    assert not s.take("nan_grad", 3)      # ...a rewind replay cannot re-fire
    assert s.armed_at("nan_grad") == [7]  # the other injection stays armed
    # disarm drops UNFIRED injections only (fired ones stay for the record)
    s.disarm("nan_grad")
    assert s.armed_at("nan_grad") == []
    assert not s.take("nan_grad", 7)
    s.arm("nan_grad", 7)
    assert s.take("nan_grad", 7)
    events = _json_lines(capsys.readouterr().out)
    fired = [e for e in events if e.get("event") == "chaos_injection"]
    assert [(e["kind"], e["step"]) for e in fired] == [("nan_grad", 3), ("nan_grad", 7)]


def test_corrupt_checkpoint_flips_the_largest_file(tmp_path, capsys):
    d = tmp_path / "step"
    os.makedirs(d)
    (d / "small.bin").write_bytes(b"x" * 64)
    (d / "large.bin").write_bytes(b"y" * 4096)
    before = (d / "large.bin").read_bytes()
    path = corrupt_checkpoint(str(d))
    assert path == str(d / "large.bin")
    assert (d / "large.bin").read_bytes() != before
    assert (d / "small.bin").read_bytes() == b"x" * 64
    assert os.path.getsize(path) == 4096  # flipped in place, not truncated
    events = _json_lines(capsys.readouterr().out)
    assert any(e.get("event") == "chaos_ckpt_corrupted" for e in events)
    # an empty/missing step dir corrupts nothing and does not raise
    assert corrupt_checkpoint(str(tmp_path / "empty")) is None


# ---------------------------------------------------------------------------
# parse-time config validation of the rewind prerequisites
# ---------------------------------------------------------------------------

def _cfg_from_cli(*argv: str) -> TrainConfig:
    p = argparse.ArgumentParser()
    add_tpu_args(p)
    return config_from_args(p.parse_args(list(argv)))


def test_config_rewind_requires_periodic_checkpointing():
    with pytest.raises(ValueError, match="--save-every-steps"):
        _cfg_from_cli("--on-anomaly", "rewind")
    with pytest.raises(ValueError, match="--recorder-steps"):
        _cfg_from_cli("--on-anomaly", "rewind", "--save-every-steps", "50",
                      "--recorder-steps", "0")
    cfg = _cfg_from_cli("--on-anomaly", "rewind", "--save-every-steps", "50",
                        "--max-rewinds", "3", "--chaos", "nan_grad@120")
    assert cfg.on_anomaly == "rewind" and cfg.max_rewinds == 3
    assert cfg.chaos == "nan_grad@120"
    with pytest.raises(ValueError, match="--max-rewinds"):
        _cfg_from_cli("--max-rewinds", "-1")
    # chaos grammar errors surface at parse time, not mid-run
    with pytest.raises(ValueError, match="kind@tick"):
        _cfg_from_cli("--chaos", "nan_grad@oops")


# ---------------------------------------------------------------------------
# checkpoint integrity: manifest sidecar, verify, fallback, save retry
# ---------------------------------------------------------------------------

def _tiny_state() -> dict:
    return {
        "w": np.arange(64, dtype=np.float32).reshape(8, 8),
        "b": np.ones((8,), np.float32),
        "step": np.zeros((), np.int32),
    }


def test_manifest_written_and_verifies(tmp_path):
    ck = Checkpointer(str(tmp_path), save_every_steps=1, async_save=False)
    ck.save(1, _tiny_state())
    ck.wait()
    assert os.path.exists(ck.manifest_path(1))
    manifest = json.load(open(ck.manifest_path(1)))
    assert manifest["step"] == 1 and manifest["files"]
    assert all(
        set(meta) == {"crc32", "size"} for meta in manifest["files"].values()
    )
    assert ck.verify(1) is None  # clean
    # corruption is caught by the manifest, named to the file
    corrupt_checkpoint(ck.step_dir(1))
    problem = ck.verify(1)
    assert problem is not None and "crc32" in problem
    ck.close()


def test_restore_falls_back_to_previous_verified_step(tmp_path, capsys):
    ck = Checkpointer(str(tmp_path), save_every_steps=1, keep=3, async_save=False)
    state = _tiny_state()
    for step in (1, 2):
        state = {**state, "step": np.asarray(step, np.int32)}
        ck.save(step, state)
    ck.wait()
    corrupt_checkpoint(ck.step_dir(2))  # the NEWEST step is torn
    restored = ck.restore_latest(abstract_like(_tiny_state()))
    assert restored is not None
    got, step = restored
    assert step == 1  # fell back instead of crashing
    assert int(got["step"]) == 1
    np.testing.assert_array_equal(got["w"], _tiny_state()["w"])
    events = _json_lines(capsys.readouterr().out)
    bad = [e for e in events if e.get("event") == "ckpt_verify_failed"]
    assert bad and bad[0]["step"] == 2
    # restore_before excludes the anomaly step itself even when clean
    assert ck.restore_before(2, abstract_like(_tiny_state()))[1] == 1
    # every retained step corrupt → None, not an exception
    corrupt_checkpoint(ck.step_dir(1))
    assert ck.restore_latest(abstract_like(_tiny_state())) is None
    ck.close()


def test_delete_after_drops_newer_steps_and_manifests(tmp_path, capsys):
    """The rewind path deletes checkpoints newer than the restore target:
    a checkpoint saved between anomaly and detection holds semantically
    poisoned state that CHECKSUMS CLEAN, and save() refuses existing
    steps, so without deletion the replay could never refresh it."""
    ck = Checkpointer(str(tmp_path), save_every_steps=1, keep=5, async_save=False)
    for step in (1, 2, 3):
        ck.save(step, _tiny_state())
    ck.wait()
    assert ck.delete_after(1) == [2, 3]
    assert ck.all_steps() == [1]
    assert not os.path.exists(ck.manifest_path(2))
    assert not os.path.exists(ck.manifest_path(3))
    assert os.path.exists(ck.manifest_path(1))
    events = _json_lines(capsys.readouterr().out)
    assert any(
        e.get("event") == "ckpt_deleted_after_rewind" and e["steps"] == [2, 3]
        for e in events
    )
    # the replay can now RE-SAVE the dropped steps from recovered state
    assert ck.save(2, _tiny_state())
    ck.wait()
    assert ck.verify(2) is None
    # nothing newer than the target → no-op
    assert ck.delete_after(10) == []
    ck.close()


def test_manifest_never_authored_for_foreign_steps(tmp_path):
    """Only the instance that SAVED a step may write its manifest: a
    restore-time instance checksumming pre-existing (possibly corrupt)
    files would baptize the corruption as verified."""
    ck1 = Checkpointer(str(tmp_path), save_every_steps=1, async_save=False)
    ck1.save(1, _tiny_state())
    ck1.close()
    os.remove(ck1.manifest_path(1))  # simulate a legacy pre-manifest step
    ck2 = Checkpointer(str(tmp_path), save_every_steps=1, async_save=False)
    restored = ck2.restore_latest(abstract_like(_tiny_state()))
    assert restored is not None and restored[1] == 1  # legacy: accepted...
    assert not os.path.exists(ck2.manifest_path(1))   # ...but never baptized
    assert ck2.verify(1) is None  # missing sidecar = legacy, not corruption
    ck2.close()


def test_save_retries_with_backoff_on_transient_io(tmp_path, capsys, monkeypatch):
    ck = Checkpointer(
        str(tmp_path), save_every_steps=1, async_save=False,
        save_retries=3, retry_backoff_s=0.01,
    )
    real_save = ck.manager.save
    calls = {"n": 0}

    def flaky(step, **kw):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient: storage mount flapped")
        return real_save(step, **kw)

    monkeypatch.setattr(ck.manager, "save", flaky)
    assert ck.save(1, _tiny_state())
    assert calls["n"] == 3
    retries = [
        e for e in _json_lines(capsys.readouterr().out)
        if e.get("event") == "ckpt_save_retry"
    ]
    assert [r["attempt"] for r in retries] == [1, 2]
    assert retries[1]["backoff_s"] > retries[0]["backoff_s"]  # exponential
    # a PERSISTENT failure still propagates once the budget is spent
    calls["n"] = -100
    monkeypatch.setattr(
        ck.manager, "save",
        lambda step, **kw: (_ for _ in ()).throw(OSError("dead mount")),
    )
    with pytest.raises(OSError, match="dead mount"):
        ck.save(2, _tiny_state())
    ck.close()


# ---------------------------------------------------------------------------
# recovery controller: escalation order, quarantine, pod-determinism
# ---------------------------------------------------------------------------

def _fp(epoch=1, epoch_step=0, crc=1234):
    return {"epoch": epoch, "epoch_step": epoch_step, "input_ids_crc32": crc}


def test_escalation_rewind_then_skip_then_halt(capsys):
    rc = RecoveryController(max_rewinds=2)
    spike = {"step": 10, "code": "loss_spike"}
    d1 = rc.decide(spike, fingerprint=_fp(epoch_step=0))
    d2 = rc.decide(spike, fingerprint=_fp(epoch_step=1))
    assert (d1.action, d2.action) == ("rewind", "rewind")
    # budget exhausted + finite state → ONE degraded skip-batch try
    d3 = rc.decide(spike, fingerprint=_fp(epoch_step=2))
    assert d3.action == "skip_batch"
    d4 = rc.decide(spike, fingerprint=_fp(epoch_step=3))
    assert d4.action == "halt"


def test_escalation_nonfinite_never_skips():
    """NaN state cannot 'continue without restore': skip-batch is only
    for finite anomalies (spike/explosion)."""
    rc = RecoveryController(max_rewinds=0)
    d = rc.decide({"step": 5, "code": "nonfinite"}, fingerprint=_fp())
    assert d.action == "halt"


def test_escalation_halts_on_requarantined_batch(capsys):
    """An anomaly recurring at an already-quarantined plan position
    refutes the poison-batch hypothesis: halt, don't loop."""
    rc = RecoveryController(max_rewinds=5)
    rc.quarantine(1, 0, _fp(), reason="anomaly:loss_spike@10")
    d = rc.decide({"step": 10, "code": "loss_spike"}, fingerprint=_fp())
    assert d.action == "halt" and "quarantined" in d.reason
    assert rc.rewinds_done == 0  # the budget was not spent on a halt


def test_quarantine_skip_checks_crc(capsys):
    rc = RecoveryController()
    batch = {"input_ids": np.arange(8, dtype=np.int32)}
    import zlib

    crc = zlib.crc32(np.ascontiguousarray(batch["input_ids"]).tobytes()) & 0xFFFFFFFF
    rc.quarantine(0, 3, _fp(epoch=0, epoch_step=3, crc=crc), reason="test")
    assert not rc.should_skip(0, 2, batch)   # un-quarantined position
    assert rc.should_skip(0, 3, batch)       # quarantined, crc matches
    events = _json_lines(capsys.readouterr().out)
    assert any(e.get("event") == "quarantine" for e in events)
    assert any(e.get("event") == "quarantine_skip" for e in events)
    assert not any(e.get("event") == "quarantine_crc_mismatch" for e in events)
    # a drifted batch at the same position still skips — but loudly
    drifted = {"input_ids": np.arange(8, dtype=np.int32) + 1}
    assert rc.should_skip(0, 3, drifted)
    events = _json_lines(capsys.readouterr().out)
    assert any(e.get("event") == "quarantine_crc_mismatch" for e in events)


# ---------------------------------------------------------------------------
# data loader robustness: transient retry + malformed-record skip
# ---------------------------------------------------------------------------

def test_load_json_records_retries_transient_errors(tmp_path, capsys, monkeypatch):
    import distributed_llms_example_tpu.data.dataset as ds

    path = str(tmp_path / "train.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"dialogue": "a", "summary": "b"}) + "\n")
    real = ds._read_json_records
    calls = {"n": 0}

    def flaky(p):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient: NFS timed out")
        return real(p)

    monkeypatch.setattr(ds, "_read_json_records", flaky)
    recs = ds.load_json_records(path, backoff_s=0.01)
    assert len(recs) == 1 and calls["n"] == 2
    # PERMANENT errors fail fast — a typo'd path must not "retry"
    with pytest.raises(FileNotFoundError):
        ds.load_json_records(str(tmp_path / "nope.jsonl"))
    events = _json_lines(capsys.readouterr().out)
    retry = next(e for e in events if e.get("event") == "data_retry")
    assert retry["attempt"] == 1 and "NFS" in retry["error"]
    # persistent failure propagates after the budget
    monkeypatch.setattr(
        ds, "_read_json_records",
        lambda p: (_ for _ in ()).throw(OSError("gone")),
    )
    with pytest.raises(OSError, match="gone"):
        ds.load_json_records(path, retries=1, backoff_s=0.01)


def test_load_json_records_skips_malformed_lines(tmp_path, capsys):
    from distributed_llms_example_tpu.data.dataset import load_json_records

    path = str(tmp_path / "corrupt.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"dialogue": "a", "summary": "b"}) + "\n")
        f.write('{"dialogue": "torn mid-wri\n')        # torn line
        f.write("[1, 2, 3]\n")                          # not a record
        f.write(json.dumps({"dialogue": "c", "summary": "d"}) + "\n")
    recs = list(load_json_records(path))
    assert [r["dialogue"] for r in recs] == ["a", "c"]
    events = _json_lines(capsys.readouterr().out)
    skip = next(e for e in events if e.get("event") == "data_skipped_records")
    assert skip["skipped"] == 2 and skip["kept"] == 2
    # a file with NO parseable record is an error, not an empty epoch
    bad = str(tmp_path / "all_bad.jsonl")
    with open(bad, "w") as f:
        f.write('{"torn": \n{"also": \n')
    with pytest.raises(ValueError, match="no parseable"):
        load_json_records(bad)
    # pretty-printed single-document JSON still takes the whole-file path
    doc = str(tmp_path / "wrapper.json")
    with open(doc, "w") as f:
        f.write('{\n  "data": [\n    {"dialogue": "x", "summary": "y"}\n  ]\n}\n')
    assert list(load_json_records(doc)) == [{"dialogue": "x", "summary": "y"}]


def test_recovery_sidecar_round_trip(tmp_path, capsys):
    """The recovery sidecar persists the (epoch, pos) cursor and the
    quarantine set next to the checkpoint: after a quarantine skip the
    cursor drifts from ``step % steps_per_epoch``, so a cross-run resume
    without it would re-train one batch and shift the rest of the
    epoch."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    from distributed_llms_example_tpu.core.mesh import build_mesh

    t = object.__new__(Trainer)
    t.checkpointer = Checkpointer(str(tmp_path), save_every_steps=1, async_save=False)
    t.recovery = RecoveryController()
    t.mesh = build_mesh(MeshConfig(data=-1))
    t.state = argparse.Namespace(ef=None)  # no error-feedback tree
    t._grad_workers = 1
    t.recovery.quarantine(1, 0, _fp(), reason="anomaly:nonfinite@3")
    Trainer._write_recovery_sidecar(t, 4, 2, 1)
    side = Trainer._load_recovery_sidecar(t, 4)
    assert (side["epoch"], side["pos"]) == (2, 1)
    assert side["quarantined"] == [[1, 0, t.recovery.quarantined[(1, 0)]]]
    # the sidecar names the saving topology (ISSUE 14): the resharding
    # restore's fail-fast pre-check reads it without touching orbax
    assert side["mesh_layout"]["axes"]["data"] == 8
    assert side["mesh_layout"]["processes"] == 1
    assert side["mesh_layout"]["ef_workers"] == 0
    assert Trainer._load_recovery_sidecar(t, 99) is None  # missing = None
    # GC'd with the step: deleting past step 0 drops step 4's sidecar
    t.checkpointer.save(4, _tiny_state())
    t.checkpointer.wait()
    t.checkpointer.delete_after(0)
    assert Trainer._load_recovery_sidecar(t, 4) is None
    t.checkpointer.close()


def test_trainer_data_retry_wrapper_and_chaos_injection(capsys):
    """The in-loop batch-fetch retry: a chaos ``data_error`` injection
    (one transient OSError) is retried away without losing a batch; a
    PERSISTENT error still propagates once the budget is spent."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    t = object.__new__(Trainer)  # _with_data_retries touches chaos/_last_step
    t.chaos = parse_chaos("data_error@2")
    t._last_step = 1  # the next step is 2 → the injection fires on fetch
    batches = [{"a": 1}, {"a": 2}, {"a": 3}]
    assert list(Trainer._with_data_retries(t, batches)) == batches
    assert t.chaos.armed_at("data_error") == []  # fired exactly once
    events = _json_lines(capsys.readouterr().out)
    assert any(e.get("event") == "chaos_injection" for e in events)
    retry = next(e for e in events if e.get("event") == "data_retry")
    assert retry["attempt"] == 1 and "chaos" in retry["error"]

    class Dead:
        def __iter__(self):
            return self

        def __next__(self):
            raise OSError("mount gone")

    t2 = object.__new__(Trainer)
    t2.chaos = ChaosSchedule()
    t2._last_step = 0
    with pytest.raises(OSError, match="mount gone"):
        list(Trainer._with_data_retries(t2, Dead()))


# ---------------------------------------------------------------------------
# the chaos e2e acceptance runs (CPU mesh, in-process Trainer)
# ---------------------------------------------------------------------------

def _records(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(12)),
            "summary": f"w{rng.randint(40)}",
        }
        for _ in range(n)
    ]


def _run_cfg(out, **over) -> TrainConfig:
    kw = dict(
        model_ckpt="t5-test",
        output_dir=str(out),
        batch_size=8,
        num_epochs=3,
        warmup_steps=1,
        evaluation_steps=0,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=2,
        num_beams=1,
        tokenizer="byte",
        mesh=MeshConfig(data=-1),
        checkpoint=CheckpointConfig(save_every_steps=2, resume=False, async_save=False),
        obs="jsonl",
        obs_gauges="off",
        health="on",
        recorder_steps=8,
    )
    kw.update(over)
    return TrainConfig(**kw)


def _leaves(params):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(params))]


@pytest.mark.slow
def test_rewind_e2e_and_bit_exact_replay(tmp_path):
    """The acceptance run: ``--chaos nan_grad@3 --on-anomaly rewind``
    FINISHES training (does not halt), emits exactly one ``recovery``
    rewind + one ``quarantine`` event, loses ≤ save_every_steps optimizer
    steps, the final loss is finite — and the post-rewind trajectory
    bit-matches a clean run that skipped the quarantined batch.  Then
    ``obs.report`` renders the recovery timeline with a finite MTTR and
    ``--strict`` passes (the only faults are injected ones)."""
    from distributed_llms_example_tpu.obs import report as report_mod
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    cfg = _run_cfg(tmp_path / "chaos", on_anomaly="rewind", chaos="nan_grad@3",
                   max_rewinds=2)
    trainer = Trainer(cfg, train_records=recs)
    trainer.save_final = lambda: None
    result = trainer.train()

    # the run FINISHED: no anomaly stop, one optimizer step lost to the
    # quarantined batch (6 planned − 1 skipped), final loss finite
    assert "anomaly" not in result
    assert result["steps"] == 5
    assert trainer.recovery.rewinds_done == 1
    # the poison batch was quarantined by plan position with its crc
    assert list(trainer.recovery.quarantined) == [(1, 0)]
    q = trainer.recovery.quarantined[(1, 0)]
    assert q["reason"] == "anomaly:nonfinite@3"
    assert q["input_ids_crc32"] is not None

    path = os.path.join(cfg.output_dir, "obs", "metrics-p000.jsonl")
    events = [json.loads(line) for line in open(path)]
    by = {}
    for e in events:
        by.setdefault(e.get("event"), []).append(e)
    # exactly one injection, one agreed anomaly, ONE rewind, ONE quarantine
    assert [(e["kind"], e["step"]) for e in by["chaos_injection"]] == [("nan_grad", 3)]
    assert len(by["obs_anomaly"]) == 1
    anomaly = by["obs_anomaly"][0]
    assert anomaly["step"] == 3 and anomaly["policy"] == "rewind"
    recovery = by["recovery"]
    assert len(recovery) == 1 and recovery[0]["action"] == "rewind"
    assert recovery[0]["restored_step"] == 2
    # detection at cadence step 4, restore to the step-2 checkpoint:
    # 2 steps lost ≤ save_every_steps
    assert recovery[0]["steps_lost"] == 2 <= cfg.checkpoint.save_every_steps
    assert recovery[0]["recovery_wall_s"] > 0
    assert len(by["quarantine"]) == 1
    assert (by["quarantine"][0]["epoch"], by["quarantine"][0]["epoch_step"]) == (1, 0)
    assert len(by["quarantine_skip"]) == 1  # the replay skipped it, once
    # final loss finite on the metric stream
    losses = [e["loss"] for e in events if "loss" in e and "step" in e]
    assert losses and np.isfinite(losses[-1])

    # obs.report: recovery timeline with a finite MTTR; --strict passes
    # because the one fault is injected
    report = build_report(cfg.output_dir)
    rec = report["recovery"]
    assert rec["rewinds"] == 1 and rec["steps_lost_total"] == 2
    assert rec["mttr_s"] is not None and rec["mttr_s"] > 0
    assert [i["kind"] for i in rec["injections"]] == ["nan_grad"]
    assert rec["organic_faults"] == []
    assert [f["injected"] for f in rec["faults"]] == [True]
    md = render_markdown(report)
    assert "Recovery timeline" in md and "rewind" in md
    assert "1 injected, 0 organic" in md
    assert report_mod.main([cfg.output_dir, "--strict"]) == 0

    # ---- the bit-exactness oracle: a clean run over the same data that
    # skips the quarantined batch from the start must land on the SAME
    # final parameters (same steps, same batches, same dropout stream)
    cfg2 = _run_cfg(tmp_path / "clean", on_anomaly="warn")
    clean = Trainer(cfg2, train_records=recs)
    clean.save_final = lambda: None
    clean.recovery.quarantine(1, 0, {}, reason="oracle")
    result2 = clean.train()
    assert result2["steps"] == 5
    for a, b in zip(_leaves(trainer.state.params), _leaves(clean.state.params)):
        np.testing.assert_array_equal(a, b)

    # ---- cross-run recovery state: a resumed Trainer over the chaos
    # run's dir restores the exact cursor AND the quarantine set from the
    # recovery sidecar (after the skip, pos drifted ahead of step % spe)
    cfg3 = _run_cfg(
        tmp_path / "chaos",
        on_anomaly="rewind", max_rewinds=2,
        checkpoint=CheckpointConfig(save_every_steps=2, resume=True, async_save=False),
    )
    resumed = Trainer(cfg3, train_records=recs)
    assert resumed.start_step == 6  # the final save
    assert resumed._resume_cursor == (3, 0)  # end-of-run cursor, exact
    assert (1, 0) in resumed.recovery.quarantined  # quarantine survived


@pytest.mark.slow
def test_ckpt_corrupt_chaos_resumes_from_previous_step(tmp_path):
    """``--chaos ckpt_corrupt@2``: the second checkpoint save is
    bit-flipped AFTER its manifest is finalized.  The next run's resume
    must fall back to the previous verified step instead of crashing —
    the exact failure mode that used to kill the resume."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    recs = _records()
    out = tmp_path / "run"
    cfg = _run_cfg(out, num_epochs=2, chaos="ckpt_corrupt@2")
    trainer = Trainer(cfg, train_records=recs)
    trainer.save_final = lambda: None
    result = trainer.train()
    assert result["steps"] == 4  # 2 epochs × 2 steps, run unaffected
    # saves landed at steps 2 and 4; the SECOND (step 4, the newest) is
    # corrupt but carries a pre-corruption manifest
    assert trainer.checkpointer.all_steps() == [2, 4]
    assert trainer.checkpointer.verify(2) is None
    assert trainer.checkpointer.verify(4) is not None

    cfg2 = _run_cfg(
        out, num_epochs=2,
        checkpoint=CheckpointConfig(save_every_steps=2, resume=True, async_save=False),
    )
    resumed = Trainer(cfg2, train_records=recs)
    resumed.save_final = lambda: None
    assert resumed.start_step == 2  # fell back past the corrupt step 4
    result2 = resumed.train()
    assert result2["steps"] == 4  # ...and finished the remaining steps
    events = [
        json.loads(line)
        for line in open(os.path.join(str(out), "obs", "metrics-p000.jsonl"))
    ]
    verify_failed = [e for e in events if e.get("event") == "ckpt_verify_failed"]
    assert verify_failed and verify_failed[0]["step"] == 4
    assert any(
        e.get("event") == "resumed" and e["step"] == 2 for e in events
    )
    # the report classifies the integrity fault as INJECTED (the
    # chaos_ckpt_corrupted event from run 1 names step 4 on the same
    # stream) → strict-green
    report = build_report(str(out))
    assert [f for f in report["recovery"]["organic_faults"]] == []
    assert any(f["kind"] == "ckpt_integrity" for f in report["recovery"]["faults"])

    # EVERY retained step corrupt → resume refuses loudly instead of
    # silently training from step 0 (which would retention-delete the
    # possibly salvageable checkpoints)
    corrupt_checkpoint(resumed.checkpointer.step_dir(2))
    with pytest.raises(ValueError, match="integrity verification"):
        Trainer(cfg2, train_records=recs)


@pytest.mark.slow
def test_final_window_rewind_degrades_to_checkpoint(tmp_path):
    """An anomaly agreed only in the FINAL partial health window has no
    loop left to replay: --on-anomaly rewind must degrade to the
    checkpoint policy (resumable save + anomaly marker), never fall
    through to save_final() exporting poisoned params as a success."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    exported = []
    cfg = _run_cfg(
        tmp_path, num_epochs=1, on_anomaly="rewind", chaos="nan_grad@2",
        log_every_steps=8,  # cadence never fires in-loop: finalize detects
    )
    trainer = Trainer(cfg, train_records=_records())
    trainer.save_final = lambda: exported.append(True)
    result = trainer.train()
    assert result.get("anomaly") == "checkpoint"
    assert exported == []  # no HF export of poisoned params
    events = [
        json.loads(line)
        for line in open(os.path.join(cfg.output_dir, "obs", "metrics-p000.jsonl"))
    ]
    assert any(e.get("event") == "obs_anomaly" and e["step"] == 2 for e in events)


# ---------------------------------------------------------------------------
# report: injected/organic split on hand-built streams
# ---------------------------------------------------------------------------

def _stamp(rec: dict) -> dict:
    return {"schema_version": 1, **rec}


def test_report_separates_injected_from_organic(tmp_path):
    from distributed_llms_example_tpu.obs import report as report_mod

    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir)
    recs = [
        _stamp({"event": "chaos_injection", "kind": "nan_grad", "step": 7}),
        _stamp({"event": "obs_anomaly", "step": 7, "detected_at_step": 8,
                "code": "nonfinite", "ranks": [0], "policy": "rewind"}),
        _stamp({"event": "recovery", "action": "rewind", "step": 7,
                "detected_at_step": 8, "code": "nonfinite",
                "restored_step": 4, "steps_lost": 4, "rewind_index": 1,
                "recovery_wall_s": 1.5, "reason": "rewind 1/2"}),
        _stamp({"event": "quarantine", "epoch": 0, "epoch_step": 6,
                "reason": "anomaly:nonfinite@7"}),
        # a SECOND rewind with the same (step, restored_step) but its own
        # rewind_index is a distinct recovery, not a per-rank copy
        _stamp({"event": "recovery", "action": "rewind", "step": 7,
                "detected_at_step": 8, "code": "nonfinite",
                "restored_step": 4, "steps_lost": 4, "rewind_index": 2,
                "recovery_wall_s": 2.5, "reason": "rewind 2/2"}),
        # ckpt integrity: step 12 was chaos-corrupted (injected), step 20
        # failed verification organically
        _stamp({"event": "chaos_injection", "kind": "ckpt_corrupt", "step": 2}),
        _stamp({"event": "chaos_ckpt_corrupted", "path": "/ck/12/d/x",
                "bytes_flipped": 64, "step": 12}),
        _stamp({"event": "ckpt_verify_failed", "step": 12, "detail": "crc32"}),
        _stamp({"event": "ckpt_verify_failed", "step": 20, "detail": "crc32"}),
        # ORGANIC: an anomaly at a step no injection explains
        _stamp({"event": "obs_anomaly", "step": 30, "detected_at_step": 30,
                "code": "loss_spike", "ranks": [1], "policy": "rewind"}),
    ]
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    # rank 1 carries duplicate copies of the local events — dedup to one row
    with open(obs_dir / "metrics-p001.jsonl", "w") as f:
        for r in recs[:4]:
            f.write(json.dumps(r) + "\n")
    report = build_report(str(tmp_path))
    rec = report["recovery"]
    assert len(rec["injections"]) == 2 and len(rec["actions"]) == 2
    assert len(rec["quarantines"]) == 1
    # the rank-1 duplicates collapsed; the rewind_index=2 row did not
    assert rec["rewinds"] == 2 and rec["steps_lost_total"] == 8
    assert rec["mttr_s"] == 2.0  # mean of 1.5 and 2.5
    kinds = {(f["kind"], f["step"], f["injected"]) for f in rec["faults"]}
    assert kinds == {
        ("anomaly:nonfinite", 7, True),
        ("anomaly:loss_spike", 30, False),
        # per-STEP match: only the chaos-corrupted step 12 is injected
        ("ckpt_integrity", 12, True),
        ("ckpt_integrity", 20, False),
    }
    assert len(rec["organic_faults"]) == 2
    md = render_markdown(report)
    assert "2 injected, 2 organic" in md and "**organic** anomaly:loss_spike" in md
    # --strict fails on the organic faults...
    assert report_mod.main([str(tmp_path), "--strict"]) == 1
    # ...and passes once only injected ones remain (incl. the injected
    # ckpt_integrity failure at the chaos-corrupted step)
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        for r in recs[:8]:
            f.write(json.dumps(r) + "\n")
    os.remove(obs_dir / "metrics-p001.jsonl")
    assert report_mod.main([str(tmp_path), "--strict"]) == 0


# ---------------------------------------------------------------------------
# 2-process leg: pod-agreed rewind (both ranks restore the same step)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_process_pod_agreed_rewind(tmp_path):
    """Two real OS processes run the full CLI with ``nan_grad@3
    --on-anomaly rewind``: the anomaly is agreed over the heartbeat
    channel, BOTH ranks restore the same checkpoint step through orbax's
    collective restore, and both finish training."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    recs = _records(32, seed=1)
    train = str(tmp_path / "train.json")
    with open(train, "w") as f:
        json.dump(recs, f)
    out = str(tmp_path / "out")
    args = [
        sys.executable, "-m", "distributed_llms_example_tpu.launch.cli",
        "--model-ckpt", "t5-test", "--output-dir", out,
        "--train-file", train, "--batch-size", "8", "--num-epochs", "2",
        "--mesh", "data=2,fsdp=2,tensor=2", "--tokenizer", "byte",
        "--max-source-length", "32", "--max-target-length", "16",
        "--pad-to-multiple", "32", "--log-every-steps", "2",
        "--num-beams", "1", "--warmup-steps", "1",
        "--obs", "jsonl", "--health", "on", "--recorder-steps", "8",
        "--on-anomaly", "rewind", "--max-rewinds", "2",
        "--save-every-steps", "2", "--chaos", "nan_grad@3",
    ]
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "PALLAS_AXON_POOL_IPS": "",
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
            "VH_MASTER_IP": f"127.0.0.1:{port}",
            "VH_WORLD_SIZE": "2",
            "VH_RANK": str(rank),
        })
        for k in ("MASTER_ADDR", "WORLD_SIZE", "RANK"):
            env.pop(k, None)
        procs.append(subprocess.Popen(
            args, env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = [p.communicate(timeout=600) for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        outs[0][1][-3000:] + outs[1][1][-3000:]
    )
    # BOTH ranks' streams carry the rewind, restored to the SAME step
    restored = []
    for rank in range(2):
        path = os.path.join(out, "obs", f"metrics-p{rank:03d}.jsonl")
        events = [json.loads(line) for line in open(path)]
        rew = [e for e in events if e.get("event") == "recovery"]
        assert len(rew) == 1 and rew[0]["action"] == "rewind", rew
        restored.append(rew[0]["restored_step"])
        assert any(e.get("event") == "quarantine" for e in events)
        assert any(e.get("event") == "chaos_injection" for e in events)
    assert restored[0] == restored[1] == 2
    # both ranks finished training after the rewind ("done", not
    # "anomaly_stop", on the p0 stdout channel)
    ev0 = _json_lines(outs[0][0])
    assert any(e.get("event") == "done" for e in ev0)
    assert not any(e.get("event") == "anomaly_stop" for e in ev0)
