"""HBM memory attribution (ISSUE 18): the bucketed byte account, the
watermark telemetry, and the OOM forensics path.

Acceptance pins held here:

- on the REAL AOT-compiled fsdp=8 t5-test train step, the static
  account's bucket bytes sum to the XLA-reported peak within 5% (with
  donation/aliasing credited), and the params/optimizer buckets equal
  ``utils/memory_audit.py``'s analytic shard-byte counts EXACTLY — both
  derive from the same shared accounting functions, so forked arithmetic
  would fail here first;
- an injected RESOURCE_EXHAUSTED produces a parseable
  ``memory-postmortem-p*.json`` bundle (atomic: tmp + fsync + rename)
  and the ``obs.report`` "Where did the bytes go" section renders from
  the JSONL/bundle files alone;
- ``--max-peak-hbm-frac`` / ``--min-hbm-headroom-gib`` gate both ways
  under ``--strict`` and FAIL a run carrying no memory measurement — a
  missing measurement must never read as a pass;
- ``Watermark`` owns the reset-or-delta semantics over the
  process-lifetime PJRT peak, and degrades by NAME (never to zeros) on
  backends without ``memory_stats``.
"""

from __future__ import annotations

import glob
import importlib.util
import json
import os

import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.obs import memprof
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.report import (
    build_report,
    main as report_main,
    render_markdown,
)


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


# ---------------------------------------------------------------------------
# Watermark: reset-or-delta semantics over the process-lifetime peak
# ---------------------------------------------------------------------------


def test_watermark_delta_semantics(monkeypatch):
    readings = [
        # two devices, asymmetric peaks: the reading maxes over devices
        [{"device": 0, "bytes_in_use": 100, "peak_bytes_in_use": 500,
          "bytes_limit": 1000},
         {"device": 1, "bytes_in_use": 90, "peak_bytes_in_use": 400,
          "bytes_limit": 1000}],
        [{"device": 0, "bytes_in_use": 200, "peak_bytes_in_use": 800,
          "bytes_limit": 1000},
         {"device": 1, "bytes_in_use": 250, "peak_bytes_in_use": 900,
          "bytes_limit": 1000}],
    ]
    monkeypatch.setattr(memprof, "hbm_stats", lambda: readings.pop(0))
    wm = memprof.Watermark()
    wm.mark()  # consumes the first reading: peaks {0: 500, 1: 400}
    r = wm.read()
    assert r["peak_bytes_in_use"] == 900
    assert r["bytes_in_use"] == 250
    # per-device deltas 300 and 500, maxed — NOT max-peak minus max-mark
    assert r["watermark_delta_bytes"] == 500
    assert r["devices"] == 2


def test_watermark_unmarked_reads_absolute_peak(monkeypatch):
    monkeypatch.setattr(memprof, "hbm_stats", lambda: [
        {"device": 0, "bytes_in_use": 10, "peak_bytes_in_use": 700,
         "bytes_limit": 1000},
    ])
    wm = memprof.Watermark()
    assert wm.read()["watermark_delta_bytes"] == 700
    assert wm.peak_bytes() == 700
    assert wm.delta_bytes() == 700


def test_watermark_absent_backend_degrades_by_name(monkeypatch):
    """No memory_stats (CPU PJRT): None/0, never fabricated zeros-as-data."""
    monkeypatch.setattr(memprof, "hbm_stats", lambda: None)
    wm = memprof.Watermark()
    wm.mark()  # no-op, must not raise
    assert wm.read() is None
    assert wm.peak_bytes() == 0
    assert wm.delta_bytes() is None


def test_hbm_stats_on_cpu_is_absent_not_zero():
    # the real backend in CI is CPU PJRT: the contract is None, not a
    # list of zero rows some gauge would happily average
    assert memprof.hbm_stats() is None


# ---------------------------------------------------------------------------
# OOM detection
# ---------------------------------------------------------------------------


def test_is_resource_exhausted_matches_the_oom_shapes():
    assert memprof.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: chaos-injected out of memory")
    )
    assert memprof.is_resource_exhausted(
        RuntimeError("Resource exhausted: Out of memory allocating "
                     "16106127360 bytes")
    )
    assert memprof.is_resource_exhausted(
        RuntimeError("Allocation failure: hbm allocator ran dry")
    )
    assert memprof.is_resource_exhausted(MemoryError())
    assert not memprof.is_resource_exhausted(ValueError("bad shape"))
    assert not memprof.is_resource_exhausted(RuntimeError("nan loss"))


# ---------------------------------------------------------------------------
# MemoryMonitor: log-cadence windows + the named CPU skip
# ---------------------------------------------------------------------------


def test_memory_monitor_emits_windows_with_per_window_deltas(
    monkeypatch, capsys
):
    seq = [
        [{"device": 0, "bytes_in_use": 100, "peak_bytes_in_use": 500,
          "bytes_limit": 1000}],
        [{"device": 0, "bytes_in_use": 150, "peak_bytes_in_use": 800,
          "bytes_limit": 1000}],
        [{"device": 0, "bytes_in_use": 150, "peak_bytes_in_use": 800,
          "bytes_limit": 1000}],  # re-mark read inside sample 1
        [{"device": 0, "bytes_in_use": 120, "peak_bytes_in_use": 800,
          "bytes_limit": 1000}],
        [{"device": 0, "bytes_in_use": 120, "peak_bytes_in_use": 800,
          "bytes_limit": 1000}],
    ]
    monkeypatch.setattr(memprof, "hbm_stats", lambda: seq.pop(0))
    mon = memprof.MemoryMonitor()
    mon.watermark.mark()
    r1 = mon.sample(2)
    r2 = mon.sample(4)
    assert r1["event"] == "memory_window" and r1["step"] == 2
    assert r1["watermark_delta_bytes"] == 300
    # the monitor re-marks after each window: a flat second window reads 0
    assert r2["watermark_delta_bytes"] == 0
    assert [h["step"] for h in mon.history] == [2, 4]
    events = _json_lines(capsys.readouterr().out)
    kinds = [e["event"] for e in events if "event" in e]
    assert kinds.count("memory_window") == 2


def test_memory_monitor_cpu_skip_is_named_and_once_only(capsys):
    mon = memprof.MemoryMonitor()
    assert mon.sample(2) is None
    assert mon.sample(4) is None
    events = _json_lines(capsys.readouterr().out)
    skips = [e for e in events if e.get("event") == "memory_window_skipped"]
    assert len(skips) == 1
    assert "static-only" in skips[0]["reason"]
    assert list(mon.history) == []


# ---------------------------------------------------------------------------
# the serving account: same taxonomy, same fit fields
# ---------------------------------------------------------------------------


def test_serving_account_buckets_and_fit_verdict():
    acct = memprof.serving_account(
        params_bytes=4 * memprof.GIB, kv_cache_bytes=2 * memprof.GIB,
        hbm_budget_gib=8.0,
    )
    assert set(acct["buckets_bytes"]) == set(memprof.BUCKETS)
    assert acct["buckets_bytes"]["params"] == 4 * memprof.GIB
    assert acct["buckets_bytes"]["kv_cache"] == 2 * memprof.GIB
    assert acct["fits_budget"] and acct["hbm_headroom_gib"] == 2.0
    over = memprof.serving_account(
        params_bytes=7 * memprof.GIB, kv_cache_bytes=2 * memprof.GIB,
        hbm_budget_gib=8.0,
    )
    assert not over["fits_budget"] and over["hbm_headroom_gib"] < 0


# ---------------------------------------------------------------------------
# THE tentpole pin: the compiled fsdp=8 account is additive and exactly
# shares the audit's analytic state-byte arithmetic
# ---------------------------------------------------------------------------


def test_static_account_is_additive_and_matches_audit_exactly():
    from distributed_llms_example_tpu.utils.memory_audit import (
        audit_train_step_memory,
    )

    mesh = build_mesh(MeshConfig(fsdp=8))
    acct = memprof.static_memory_account(
        "t5-test", mesh, global_batch=8, src_len=64, tgt_len=16,
    )
    # additivity: buckets sum to the XLA peak within 5% (donation
    # credited — outputs enter only net of aliased bytes)
    peak = acct["peak_bytes"]
    assert peak > 0
    assert abs(acct["bucket_total_bytes"] - peak) <= 0.05 * peak
    assert abs(acct["additivity_gap_bytes"]) <= 0.05 * peak
    # donation really was credited: the raw output bytes alone exceed
    # what the 'other' bucket absorbed
    view = acct["compiled"]
    assert view["aliased_bytes"] > 0
    assert acct["buckets_bytes"]["other"] < view["output_bytes"]
    # EXACT equality with the audit's analytic per-bucket state bytes:
    # same function, same numbers — not approximately, not rounded
    audit = audit_train_step_memory(
        "t5-test", mesh_config=MeshConfig(fsdp=8),
        global_batch=8, src_len=64, tgt_len=16,
    )
    sb = audit["analytic_state_bucket_bytes"]
    assert acct["buckets_bytes"]["params"] == sb["params"]
    assert acct["buckets_bytes"]["optimizer_state"] == sb["optimizer_state"]
    assert acct["buckets_bytes"]["grad_accum"] == sb.get("grad_accum", 0)
    assert audit["analytic_state_bytes"] == sum(sb.values())
    # the largest-buffers listing names real sharded state leaves
    top = acct["largest_buffers"]
    assert top and all(r["bytes"] > 0 for r in top)
    assert any("embedding" in r["name"] for r in top)
    # fsdp=8 shards the big leaves: shard bytes < replicated bytes
    import numpy as np

    biggest = top[0]
    assert (
        int(np.prod(biggest["shard_shape"]))
        < int(np.prod(biggest["shape"]))
        or biggest["shape"] == biggest["shard_shape"]  # tiny leaves stay whole
    )
    # the grad_accum bucket (TrainState.ef error-feedback) exists even
    # when EF is absent — 0, not missing (absent beats zero is for
    # MEASUREMENTS; the taxonomy itself is total)
    assert acct["buckets_bytes"]["grad_accum"] == 0  # no EF without int8


# ---------------------------------------------------------------------------
# postmortem bundles: atomic, parseable, schema-stamped
# ---------------------------------------------------------------------------


def test_dump_postmortem_atomic_and_parseable(tmp_path, capsys):
    acct = memprof.serving_account(
        params_bytes=123, kv_cache_bytes=456, hbm_budget_gib=1.0,
    )
    path = memprof.dump_postmortem(
        str(tmp_path),
        reason="RuntimeError: RESOURCE_EXHAUSTED: injected",
        step=7,
        account=acct,
        watermark_history=[{"step": 5, "bytes_in_use": 9}],
    )
    assert path == os.path.join(str(tmp_path), "obs",
                                "memory-postmortem-p000.json")
    # atomic discipline: the tmp staging file is gone, the bundle parses
    assert not os.path.exists(path + ".tmp")
    bundle = json.load(open(path))
    assert bundle["schema_version"] == sink_mod.SCHEMA_VERSION
    assert bundle["event"] == "memory_postmortem"
    assert bundle["step"] == 7 and "RESOURCE_EXHAUSTED" in bundle["reason"]
    assert bundle["account"]["buckets_bytes"]["params"] == 123
    assert bundle["watermark_history"] == [{"step": 5, "bytes_in_use": 9}]
    events = _json_lines(capsys.readouterr().out)
    ann = [e for e in events if e.get("event") == "memory_postmortem"]
    assert len(ann) == 1 and ann[0]["path"] == path


def test_maybe_dump_postmortem_fires_only_on_oom(tmp_path):
    mon = memprof.MemoryMonitor()
    assert mon.maybe_dump_postmortem(
        str(tmp_path), step=3, error=ValueError("not an oom"),
    ) is None
    assert glob.glob(str(tmp_path / "obs" / "memory-postmortem-*")) == []
    path = mon.maybe_dump_postmortem(
        str(tmp_path), step=3,
        error=RuntimeError("RESOURCE_EXHAUSTED: out of memory"),
    )
    assert path is not None and os.path.exists(path)


def test_dump_postmortem_io_failure_never_raises(tmp_path, capsys):
    """Telemetry never takes down the run: an unwritable output dir is a
    named failure event, not an exception on the crash path."""
    blocker = tmp_path / "obs"
    blocker.write_text("a file where the obs dir should be")
    path = memprof.dump_postmortem(
        str(tmp_path), reason="RESOURCE_EXHAUSTED", step=1,
    )
    assert path is None
    events = _json_lines(capsys.readouterr().out)
    assert any(e.get("event") == "memory_postmortem_failed" for e in events)


# ---------------------------------------------------------------------------
# report: "Where did the bytes go" from the JSONL/bundle files alone
# ---------------------------------------------------------------------------


def _write_jsonl(tmp_path, records):
    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir, exist_ok=True)
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        for r in records:
            f.write(json.dumps({"schema_version": 1, **r}) + "\n")
    return str(tmp_path)


def _account_event(**over):
    acct = memprof.serving_account(
        params_bytes=4 * memprof.GIB, kv_cache_bytes=0, hbm_budget_gib=16.0,
    )
    acct["buckets_bytes"]["activations"] = memprof.GIB
    acct.update(
        event="memory_account", model="t5-test", mesh={"fsdp": 8},
        backend="tpu", additivity_gap_bytes=0, largest_buffers=[
            {"name": ".params['shared']['embedding']", "shape": [256, 64],
             "shard_shape": [32, 64], "dtype": "float32", "bytes": 8192,
             "module": "embed"},
        ],
    )
    acct.update(over)
    return acct


def test_report_memory_section_round_trips_from_jsonl(tmp_path):
    d = _write_jsonl(tmp_path, [
        {"step": 1, "loss": 2.0},
        _account_event(),
        {"event": "memory_window", "step": 2, "bytes_in_use": 5 * memprof.GIB,
         "peak_bytes_in_use": 6 * memprof.GIB, "watermark_delta_bytes": 0,
         "bytes_limit": 16 * memprof.GIB, "devices": 8},
        {"event": "memory_window", "step": 4, "bytes_in_use": 5 * memprof.GIB,
         "peak_bytes_in_use": 7 * memprof.GIB,
         "watermark_delta_bytes": memprof.GIB,
         "bytes_limit": 16 * memprof.GIB, "devices": 8},
    ])
    rep = build_report(d)
    mem = rep["memory"]
    assert mem["account"]["peak_bytes"] == 4 * memprof.GIB
    assert mem["runtime"]["windows"] == 2
    assert mem["runtime"]["peak_bytes_in_use"] == 7 * memprof.GIB
    assert mem["runtime"]["max_watermark_delta_bytes"] == memprof.GIB
    # a runtime sample outranks the static account as THE measured peak
    assert mem["measured_peak_bytes"] == 7 * memprof.GIB
    assert mem["measured_peak_source"] == "memory_window"
    assert not mem["static_only"]
    md = render_markdown(rep)
    assert "## Where did the bytes go" in md
    assert "| params |" in md and "share of peak" in md
    assert ".params['shared']['embedding']" in md


def test_report_memory_static_only_names_the_skip(tmp_path):
    d = _write_jsonl(tmp_path, [
        _account_event(),
        {"event": "memory_window_skipped", "step": 2,
         "reason": "backend reports no memory_stats (CPU PJRT) — memory "
                   "account degrades to static-only"},
    ])
    rep = build_report(d)
    mem = rep["memory"]
    assert mem["static_only"] and mem["runtime"] is None
    assert mem["measured_peak_source"] == "static_account"
    assert "static-only" in render_markdown(rep)


def test_report_renders_over_budget_account(tmp_path):
    acct = _account_event()
    acct.update(
        peak_bytes=20 * memprof.GIB, peak_gib=20.0, fits_budget=False,
        hbm_headroom_gib=-4.0, peak_frac_of_budget=1.25,
    )
    d = _write_jsonl(tmp_path, [acct])
    md = render_markdown(build_report(d))
    assert "OVER BUDGET" in md


def test_report_memory_section_absent_without_events(tmp_path):
    d = _write_jsonl(tmp_path, [{"step": 1, "loss": 1.0}])
    rep = build_report(d)
    assert rep["memory"] is None
    assert "Where did the bytes go" not in render_markdown(rep)


def test_report_surfaces_postmortem_bundles(tmp_path):
    d = _write_jsonl(tmp_path, [_account_event()])
    memprof.dump_postmortem(
        d, reason="RuntimeError: RESOURCE_EXHAUSTED: injected", step=9,
        account=_account_event(),
        watermark_history=[{"step": 8, "bytes_in_use": 1}],
    )
    rep = build_report(d)
    pm = rep["memory"]["postmortems"]
    assert pm["0"]["step"] == 9 and pm["0"]["has_account"]
    assert pm["0"]["watermark_samples"] == 1
    assert "OOM postmortem" in render_markdown(rep)


def test_report_rejects_torn_postmortem_as_error(tmp_path):
    d = _write_jsonl(tmp_path, [{"step": 1, "loss": 1.0}])
    obs_dir = os.path.join(d, "obs")
    with open(os.path.join(obs_dir, "memory-postmortem-p000.json"), "w") as f:
        f.write('{"schema_version": 1, "truncated')
    rep = build_report(d)
    assert any("memory-postmortem" in e for e in rep["schema_errors"])


# ---------------------------------------------------------------------------
# strict gates: both directions, and missing-measurement fails
# ---------------------------------------------------------------------------


def test_strict_memory_gates_pass_and_fail(tmp_path, capsys):
    d = _write_jsonl(tmp_path, [{"step": 1, "loss": 1.0}, _account_event()])
    # peak_frac_of_budget = 5/16 GiB ≈ 0.3125 (params 4 GiB + act 1 GiB)
    assert report_main(
        [d, "--strict", "--max-peak-hbm-frac", "0.9",
         "--min-hbm-headroom-gib", "1.0", "--json"]
    ) == 0
    assert report_main(
        [d, "--strict", "--max-peak-hbm-frac", "0.2", "--json"]
    ) == 1
    assert "exceeds" in capsys.readouterr().err
    assert report_main(
        [d, "--strict", "--min-hbm-headroom-gib", "14.0", "--json"]
    ) == 1
    assert "below the" in capsys.readouterr().err


def test_strict_memory_gates_fail_without_measurement(tmp_path, capsys):
    """THE acceptance pin: --max-peak-hbm-frac on a run with no memory
    measurement fails — a missing measurement must never read as a
    pass."""
    d = _write_jsonl(tmp_path, [{"step": 1, "loss": 1.0}])
    assert report_main([d, "--strict", "--json"]) == 0  # clean sans gate
    assert report_main(
        [d, "--strict", "--max-peak-hbm-frac", "0.9", "--json"]
    ) == 1
    assert "no memory measurement" in capsys.readouterr().err
    assert report_main(
        [d, "--strict", "--min-hbm-headroom-gib", "1.0", "--json"]
    ) == 1
    assert "no memory account" in capsys.readouterr().err


def test_obs_gate_passes_memory_flags_through(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "obs_gate",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "obs_gate.py"),
    )
    obs_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_gate)
    seen = {}

    def fake_main(flags):
        seen["flags"] = flags
        return 0

    import distributed_llms_example_tpu.obs.report as report_mod

    monkeypatch.setattr(report_mod, "main", fake_main)
    assert obs_gate.main([
        str(tmp_path), "--max-peak-hbm-frac", "0.85",
        "--min-hbm-headroom-gib", "2.0",
    ]) == 0
    flags = seen["flags"]
    i = flags.index("--max-peak-hbm-frac")
    assert flags[i + 1] == "0.85"
    j = flags.index("--min-hbm-headroom-gib")
    assert flags[j + 1] == "2.0"
    # off by default
    assert obs_gate.main([str(tmp_path)]) == 0
    assert "--max-peak-hbm-frac" not in seen["flags"]


# ---------------------------------------------------------------------------
# bench_diff directions for the memory leaves
# ---------------------------------------------------------------------------


def test_bench_diff_directions_for_memory_leaves():
    spec = importlib.util.spec_from_file_location(
        "bench_diff",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_diff.py"),
    )
    bench_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_diff)
    d = bench_diff.direction_of
    # memory moving up is a regression
    assert d("grad_accum.accum4.peak_hbm_new_high_water_gib") == -1
    assert d("grad_accum.accum4.peak_hbm_gib_cumulative") == -1
    assert d("memory_watermark.bytes_in_use") == -1
    assert d("memory_account.peak_frac_of_budget") == -1
    # headroom under the budget is the higher-better face
    assert d("memory_account.hbm_headroom_gib") == 1
    assert d("serve.hbm_headroom_gib") == 1
    # the budget itself is a config knob, never a regression
    assert d("memory_account.hbm_budget_gib") == 0
    assert d("memory_account.hbm_budget_bytes") == 0


# ---------------------------------------------------------------------------
# lint --memory: the account as findings, skips by name
# ---------------------------------------------------------------------------


def test_lint_memory_pass_emits_account_and_over_budget():
    """ONE compile exercises both faces: the info ``memory-account``
    finding always lands, and a budget the step cannot fit turns into
    an error ``memory-over-budget`` (the fits_budget=True face is
    pinned on the account itself in the additivity test above)."""
    from distributed_llms_example_tpu.analysis.lint import run_passes

    findings = run_passes(
        model="t5-test", mesh_cfg=MeshConfig(fsdp=8),
        global_batch=8, src_len=64, tgt_len=16,
        memory=True, hbm_budget_gib=0.001,  # ~1 MiB: anything overflows
    )
    acct = [f for f in findings if f.code == "memory-account"]
    assert len(acct) == 1 and acct[0].severity == "info"
    assert not acct[0].context["fits_budget"]
    assert set(acct[0].context["buckets_bytes"]) == set(memprof.BUCKETS)
    over = [f for f in findings if f.code == "memory-over-budget"]
    assert len(over) == 1 and over[0].severity == "error"
    assert "exceeds" in over[0].message


def test_lint_memory_skip_is_named_when_ir_cannot_compile():
    from distributed_llms_example_tpu.analysis.lint import run_passes

    findings = run_passes(
        model="t5-test", mesh_cfg=MeshConfig(fsdp=8),
        run_ir=False, memory=True,
    )
    skips = [f for f in findings if f.code == "memory-account-skipped"]
    assert len(skips) == 1
    assert not [f for f in findings if f.code == "memory-account"]


# ---------------------------------------------------------------------------
# the e2e kill path: chaos oom@K through the real Trainer
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_oom_e2e_dumps_postmortem_and_reraises(tmp_path):
    import numpy as np

    from distributed_llms_example_tpu.core.config import (
        CheckpointConfig,
        TrainConfig,
    )
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(0)
    recs = [
        {"dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(12)),
         "summary": f"w{rng.randint(40)}"}
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="t5-test", output_dir=str(tmp_path), batch_size=8,
        num_epochs=1, warmup_steps=1, evaluation_steps=0,
        max_source_length=32, max_target_length=16, pad_to_multiple=32,
        log_every_steps=1, num_beams=1, tokenizer="byte",
        mesh=MeshConfig(data=-1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False,
                                    async_save=False),
        obs="jsonl", obs_gauges="on", health="on", chaos="oom@2",
    )
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        Trainer(cfg, train_records=recs).train()
    # the bundle landed atomically and parses
    paths = glob.glob(str(tmp_path / "obs" / "memory-postmortem-p*.json"))
    assert len(paths) == 1
    bundle = json.load(open(paths[0]))
    assert bundle["event"] == "memory_postmortem"
    assert "RESOURCE_EXHAUSTED" in bundle["reason"]
    # the startup account was attached to the bundle (obs_gauges on)
    assert bundle["account"] is not None
    assert bundle["account"]["buckets_bytes"]["params"] > 0
    # the report renders the whole story from the files alone
    rep = build_report(str(tmp_path))
    mem = rep["memory"]
    assert mem["account"]["additivity_gap_bytes"] == 0
    assert mem["postmortems"]["0"]["has_account"]
    md = render_markdown(rep)
    assert "Where did the bytes go" in md and "OOM postmortem" in md
    # and the gates run off it
    assert report_main(
        [str(tmp_path), "--strict", "--max-peak-hbm-frac", "0.9", "--json"]
    ) == 0
