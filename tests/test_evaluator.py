"""End-to-end eval loop smoke test on the 8-device mesh."""

import jax
import numpy as np

from distributed_llms_example_tpu.data.dataset import SummarizationDataset
from distributed_llms_example_tpu.data.tokenizer import ByteTokenizer
from distributed_llms_example_tpu.evaluation.evaluate import Evaluator
from distributed_llms_example_tpu.evaluation.metrics import aggregate_mean
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.parallel.sharding import shard_params


def test_evaluator_end_to_end(mesh8):
    tok = ByteTokenizer()
    records = [{"dialogue": f"hello world number {i}", "summary": f"num {i}"} for i in range(10)]
    ds = SummarizationDataset(records, tok, max_source_length=64, max_target_length=16)
    lm = load_model("t5-test")
    params = shard_params(lm.init_params(0), mesh8)
    ev = Evaluator(lm.module, lm.config, tok, mesh8, num_beams=2, max_new_tokens=16)
    scores = ev.run(params, ds, global_batch=8, bucket_multiple=32, max_source_length=64)
    assert set(scores) >= {"rouge1", "rouge2", "rougeL", "rougeLsum"}
    for v in scores.values():
        assert 0.0 <= v <= 1.0 and np.isfinite(v)


def test_evaluator_greedy_path(mesh8):
    tok = ByteTokenizer()
    records = [{"dialogue": "abc", "summary": "ab"}] * 4
    ds = SummarizationDataset(records, tok, max_source_length=32, max_target_length=8)
    lm = load_model("t5-test")
    params = shard_params(lm.init_params(1), mesh8)
    ev = Evaluator(lm.module, lm.config, tok, mesh8, num_beams=1, max_new_tokens=8)
    scores = ev.run(params, ds, global_batch=4, bucket_multiple=32, max_source_length=32)
    assert "rouge1" in scores


def test_aggregate_mean_single_process():
    out = aggregate_mean({"rouge1": 0.5, "epoch": 3})
    assert out == {"rouge1": 0.5, "epoch": 3.0}
