"""Generation parity vs HF torch generate() on identical random weights."""

import numpy as np
import pytest

from distributed_llms_example_tpu.evaluation.generation import make_beam_search, make_greedy_generate
from distributed_llms_example_tpu.models.convert import convert_t5_state_dict
from distributed_llms_example_tpu.models.t5 import T5Config, T5ForConditionalGeneration

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def pair():
    hf_cfg = transformers.T5Config(
        vocab_size=64,
        d_model=32,
        d_kv=8,
        d_ff=64,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=32,
        dropout_rate=0.0,
        decoder_start_token_id=0,
        eos_token_id=1,
        pad_token_id=0,
    )
    torch.manual_seed(7)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = T5Config(
        vocab_size=64, d_model=32, d_kv=8, d_ff=64, num_layers=2, num_decoder_layers=2,
        num_heads=4, relative_attention_num_buckets=8, relative_attention_max_distance=32,
        dropout_rate=0.0,
    )
    model = T5ForConditionalGeneration(cfg)
    params = convert_t5_state_dict(hf_model.state_dict())
    return hf_model, model, cfg, params


def _inputs(b=3, s=10, vocab=64, seed=3):
    rng = np.random.RandomState(seed)
    ids = rng.randint(2, vocab, (b, s)).astype(np.int32)
    mask = np.ones((b, s), np.int32)
    mask[1, -4:] = 0
    return ids, mask


def _hf_generate(hf_model, ids, mask, max_new, beams):
    out = hf_model.generate(
        input_ids=torch.tensor(ids, dtype=torch.long),
        attention_mask=torch.tensor(mask, dtype=torch.long),
        max_length=max_new + 1,  # HF counts the decoder start token
        num_beams=beams,
        do_sample=False,
        early_stopping=False,
        length_penalty=1.0,
    )
    return out.numpy()[:, 1:]  # strip decoder start


def _canon(row, eos=1, pad=0):
    """Tokens up to and including first eos, pads stripped."""
    out = []
    for t in row.tolist():
        out.append(int(t))
        if t == eos:
            break
    return [t for t in out if t != pad or True]


def test_greedy_parity(pair):
    hf_model, model, cfg, params = pair
    ids, mask = _inputs()
    max_new = 12
    ref = _hf_generate(hf_model, ids, mask, max_new, beams=1)
    gen = make_greedy_generate(model, cfg, max_new)
    got = np.asarray(gen(params, ids, mask))
    for i in range(ids.shape[0]):
        assert _canon(got[i]) == _canon(ref[i]), (i, got[i], ref[i])


def test_beam_parity(pair):
    hf_model, model, cfg, params = pair
    ids, mask = _inputs(seed=11)
    max_new = 10
    ref = _hf_generate(hf_model, ids, mask, max_new, beams=2)
    gen = make_beam_search(model, cfg, max_new, num_beams=2, length_penalty=1.0)
    got = np.asarray(gen(params, ids, mask))
    for i in range(ids.shape[0]):
        assert _canon(got[i]) == _canon(ref[i]), (i, got[i], ref[i])


def test_greedy_stops_at_eos(pair):
    _, model, cfg, params = pair
    ids, mask = _inputs(seed=5)
    gen = make_greedy_generate(model, cfg, 16)
    got = np.asarray(gen(params, ids, mask))
    for row in got:
        row = row.tolist()
        if cfg.eos_token_id in row:
            k = row.index(cfg.eos_token_id)
            assert all(t == cfg.pad_token_id for t in row[k + 1 :])
