"""Data pipeline tests: loading, columns, partitioning, bucketed batching."""

import json

import numpy as np
import pytest

from distributed_llms_example_tpu.data.batching import LABEL_PAD, BatchIterator, bucket_len, make_batch
from distributed_llms_example_tpu.data.dataset import (
    SummarizationDataset,
    epoch_order,
    host_batch_slices,
    iter_global_batches,
    load_json_records,
    partition_indices,
    resolve_columns,
)
from distributed_llms_example_tpu.data.tokenizer import ByteTokenizer, get_tokenizer


def _records(n=20):
    return [{"dialogue": f"speaker A says thing {i} " * (i % 5 + 1), "summary": f"thing {i}"} for i in range(n)]


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(obj if isinstance(obj, str) else json.dumps(obj))
    return str(p)


def test_load_json_array(tmp_path):
    p = _write(tmp_path, "d.json", _records(3))
    assert len(load_json_records(p)) == 3


def test_load_jsonl(tmp_path):
    lines = "\n".join(json.dumps(r) for r in _records(4))
    p = _write(tmp_path, "d.jsonl", lines)
    assert len(load_json_records(p)) == 4


def test_load_data_wrapper(tmp_path):
    p = _write(tmp_path, "d.json", {"data": _records(2)})
    assert len(load_json_records(p)) == 2


def test_resolve_columns_both_schemas():
    assert resolve_columns({"dialogue": "x", "summary": "y"}) == ("dialogue", "summary")
    # the reference's dead-code path schema (train-task.py:125-126)
    assert resolve_columns({"article": "x", "highlights": "y"}) == ("article", "highlights")
    with pytest.raises(ValueError, match="cannot find"):
        resolve_columns({"foo": 1})


def test_partition_indices_reference_semantics():
    # fractional split, deterministic under the reference's seed
    parts = partition_indices(100, [0.7, 0.2, 0.1], seed=1234)
    assert [len(p) for p in parts] == [70, 20, 10]
    assert sorted(sum(parts, [])) == list(range(100))
    assert parts == partition_indices(100, [0.7, 0.2, 0.1], seed=1234)
    # live-path usage: equal shards per rank (train-task.py:181)
    world = 4
    shards = partition_indices(100, [1 / world] * world)
    assert all(len(s) == 25 for s in shards)
    assert len({tuple(s) for s in shards}) == world  # disjoint


def test_epoch_order_deterministic_and_epoch_dependent():
    a = epoch_order(50, seed=7, epoch=0)
    b = epoch_order(50, seed=7, epoch=0)
    c = epoch_order(50, seed=7, epoch=1)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_bucketing():
    assert bucket_len(1, 128, 1024) == 128
    assert bucket_len(129, 128, 1024) == 256
    assert bucket_len(5000, 128, 1024) == 1024


def test_dataset_and_batch_shapes():
    tok = ByteTokenizer()
    ds = SummarizationDataset(_records(10), tok, max_source_length=256, max_target_length=64)
    assert len(ds) == 10
    assert ds[0].input_ids[-1] == tok.eos_id
    batch = make_batch(ds, np.arange(4), pad_id=tok.pad_id, bucket_multiple=32,
                       max_source_length=256, max_target_length=64)
    b, s = batch["input_ids"].shape
    assert b == 4 and s % 32 == 0 and s <= 256
    assert batch["labels"].shape[0] == 4
    assert (batch["labels"] == LABEL_PAD).any()
    assert batch["attention_mask"].sum(axis=1).min() > 0


def test_multihost_agreement():
    """4 simulated hosts must see disjoint slices, identical shapes, and the
    union of a global batch — the determinism contract."""
    tok = ByteTokenizer()
    ds = SummarizationDataset(_records(64), tok, max_source_length=128, max_target_length=32)
    iters = [
        BatchIterator(
            ds, global_batch=16, process_count=4, process_index=r, seed=5,
            bucket_multiple=32, max_source_length=128, max_target_length=32,
        )
        for r in range(4)
    ]
    assert all(it.steps_per_epoch() == 4 for it in iters)
    per_host = [list(it.epoch(0)) for it in iters]
    for step in range(4):
        shapes = {h[step]["input_ids"].shape for h in per_host}
        assert len(shapes) == 1  # same bucket on every host
        assert next(iter(shapes))[0] == 4  # 16 global / 4 hosts
    # reconstruct the global first batch and compare to the global index stream
    global_idx = next(iter_global_batches(64, 16, seed=5, epoch=0))
    rebuilt = np.concatenate([h[0]["labels"] for h in per_host], axis=0)
    expect = make_batch(ds, global_idx, pad_id=tok.pad_id, bucket_multiple=32,
                        max_source_length=128, max_target_length=32)["labels"]
    np.testing.assert_array_equal(rebuilt, expect)


def test_wraparound_batch():
    ds = SummarizationDataset(_records(10), ByteTokenizer(), max_source_length=64, max_target_length=32)
    it = BatchIterator(ds, global_batch=4, seed=0, drop_last=False, bucket_multiple=32,
                       max_source_length=64, max_target_length=32)
    batches = list(it.epoch(0))
    assert len(batches) == 3 == it.steps_per_epoch()
    assert all(b["input_ids"].shape[0] == 4 for b in batches)


def test_get_tokenizer_fallback():
    tok = get_tokenizer("", "t5-small")  # not a dir → byte fallback
    assert isinstance(tok, ByteTokenizer)
    rt = tok.decode(tok.encode("héllo wörld"))
    assert rt == "héllo wörld"


def test_host_batch_slices():
    assert host_batch_slices(16, 4, 1) == slice(4, 8)
    with pytest.raises(ValueError, match="not divisible"):
        host_batch_slices(10, 4, 0)


def test_batch_encode_matches_per_example():
    """ensure_encoded (the pod-host feed-rate path: one Rust-parallel
    tokenizer call per batch) must produce byte-identical ids to the lazy
    per-example __getitem__ path, for both tokenizer kinds."""
    from distributed_llms_example_tpu.data.dataset import SummarizationDataset
    from distributed_llms_example_tpu.data.tokenizer import ByteTokenizer

    records = [
        {"dialogue": f"hello world {i} " * (i + 1), "summary": f"sum {i}"}
        for i in range(9)
    ]
    tok = ByteTokenizer()
    a = SummarizationDataset(records, tok, max_source_length=32, max_target_length=8)
    b = SummarizationDataset(records, tok, max_source_length=32, max_target_length=8)
    b.ensure_encoded(range(len(records)))
    for i in range(len(records)):
        assert a[i].input_ids == b[i].input_ids
        assert a[i].labels == b[i].labels


def test_batch_encode_matches_per_example_hf(tmp_path):
    """Same contract through a real transformers fast tokenizer (the
    construction tests/test_tokenizer_parity.py uses)."""
    pytest.importorskip("tokenizers")
    from tokenizers import Tokenizer as TK, models, pre_tokenizers, processors
    from tokenizers.trainers import BpeTrainer
    from transformers import PreTrainedTokenizerFast

    from distributed_llms_example_tpu.data.dataset import SummarizationDataset
    from distributed_llms_example_tpu.data.tokenizer import HFTokenizer

    records = [
        {"dialogue": "the quick brown fox " * (i + 1), "summary": "a fox"}
        for i in range(7)
    ]
    tok = TK(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    trainer = BpeTrainer(special_tokens=["<s>", "<pad>", "</s>", "<unk>"], vocab_size=300)
    tok.train_from_iterator([r["dialogue"] for r in records], trainer)
    bos, eos = tok.token_to_id("<s>"), tok.token_to_id("</s>")
    tok.post_processor = processors.TemplateProcessing(
        single="<s> $A </s>", pair="<s> $A </s> $B </s>",
        special_tokens=[("<s>", bos), ("</s>", eos)],
    )
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token="<s>", eos_token="</s>",
        pad_token="<pad>", unk_token="<unk>",
    )
    d = str(tmp_path / "tok")
    fast.save_pretrained(d)
    hf = HFTokenizer(d)
    a = SummarizationDataset(records, hf, max_source_length=16, max_target_length=8)
    b = SummarizationDataset(records, hf, max_source_length=16, max_target_length=8)
    b.ensure_encoded(range(len(records)))
    for i in range(len(records)):
        assert a[i].input_ids == b[i].input_ids
        assert a[i].labels == b[i].labels


def test_epoch_start_step_resumes_without_assembly():
    """In-epoch resume skips at the INDEX level: epoch(e, start_step=N)
    yields exactly the batches epoch(e) yields from step N on, and the
    skipped batches' examples are never tokenized (round-4 fast-forward
    assembled and discarded them — O(N) host work before the first real
    step)."""

    class CountingByte(ByteTokenizer):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def encode_source(self, text, max_length):
            self.calls += 1
            return super().encode_source(text, max_length)

        encode_target = encode_source

        def encode_source_batch(self, texts, max_length):
            self.calls += len(texts)
            return [ByteTokenizer.encode_source(self, t, max_length) for t in texts]

        encode_target_batch = encode_source_batch

    records = [{"dialogue": f"word {i} " * (i % 5 + 1), "summary": f"s {i}"} for i in range(32)]

    def make_iter():
        from distributed_llms_example_tpu.data.dataset import SummarizationDataset

        tok = CountingByte()
        ds = SummarizationDataset(records, tok, max_source_length=64, max_target_length=16)
        return tok, BatchIterator(
            ds, global_batch=8, seed=5, bucket_multiple=16,
            max_source_length=64, max_target_length=16,
        )

    _, it_full = make_iter()
    full = list(it_full.epoch(0))
    assert len(full) == 4

    tok, it_tail = make_iter()
    tail = list(it_tail.epoch(0, start_step=3))
    assert len(tail) == 1
    for k in full[3]:
        np.testing.assert_array_equal(tail[0][k], full[3][k])
    # only the ONE remaining batch's examples were encoded (src + tgt each)
    assert tok.calls == 2 * 8


def test_microbatch_size_contract():
    """The (global batch, accumulation, sharding) validation: one iterator
    batch stays one optimizer step; every failure names the offending
    numbers."""
    from distributed_llms_example_tpu.data.batching import microbatch_size

    assert microbatch_size(16, 4) == 4
    assert microbatch_size(16, 4, batch_shards=4, process_count=2) == 4
    assert microbatch_size(8, 1, batch_shards=8) == 8
    with pytest.raises(ValueError, match="grad_accum_steps"):
        microbatch_size(16, 0)
    with pytest.raises(ValueError, match="not divisible"):
        microbatch_size(10, 4)
    with pytest.raises(ValueError, match="batch shards"):
        microbatch_size(16, 4, batch_shards=8)
    with pytest.raises(ValueError, match="processes"):
        microbatch_size(16, 4, batch_shards=2, process_count=3)
