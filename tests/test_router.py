"""Fault-tolerant serving tier (ISSUE 15): the replica router.

Acceptance pins: ``replica_crash@K`` mid-decode → every in-flight
request completes on a surviving replica with greedy tokens BIT-IDENTICAL
to the unfailed oracle run, zero requests lost, ``obs.report --strict``
green (injected-only) and a finite request-level MTTR in the recovery
timeline; the health machine (live → suspect → dead, heartbeat-miss /
step-stall detection) on deterministic fake replicas; bounded retry with
tick-unit exponential backoff and retry-exhaustion shedding; admission
control (shed/defer over the queue bound) incl. the ``request_storm``
chaos burst never starving real traffic; per-request deadlines; graceful
drain losing zero requests with nothing persisted (serving is stateless
by construction — proven, not asserted); session→replica affinity with
failover remap; the stepwise ``ServeSession`` engine API (incremental
submit == batch generate); the crash-safe product JSONL writer under
kill -9; and the report/obs_gate serving gates
(--max-request-retry-rate / --min-serve-goodput-frac).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.chaos import parse_chaos
from distributed_llms_example_tpu.obs.report import build_report, render_markdown
from distributed_llms_example_tpu.serving.engine import (
    ServeConfig,
    ServingEngine,
    trim_eos,
)
from distributed_llms_example_tpu.serving.router import (
    ReplicaRouter,
    RouterConfig,
)
from distributed_llms_example_tpu.utils.backoff import backoff_ticks


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


# ---------------------------------------------------------------------------
# pure logic: config, backoff, chaos grammar
# ---------------------------------------------------------------------------


def test_router_config_validation():
    with pytest.raises(ValueError, match="shed_policy"):
        RouterConfig(shed_policy="drop")
    with pytest.raises(ValueError, match="max_retries"):
        RouterConfig(max_retries=-1)
    with pytest.raises(ValueError, match="dead_after_ticks"):
        RouterConfig(suspect_after_ticks=5, dead_after_ticks=5)


def test_backoff_ticks_schedule():
    assert backoff_ticks(0) == 0
    assert [backoff_ticks(r, base=2, cap=16) for r in (1, 2, 3, 4, 5)] == [
        2, 4, 8, 16, 16,
    ]


def test_chaos_grammar_serving_kinds():
    s = parse_chaos("replica_crash@4,replica_stall@9,request_storm@2")
    assert s.armed_at("replica_crash") == [4]
    assert s.armed_at("replica_stall") == [9]
    assert s.armed_at("request_storm") == [2]
    with pytest.raises(ValueError, match="replica"):
        parse_chaos("replica_crash@")
    with pytest.raises(ValueError, match="kind@tick"):
        parse_chaos("replica_boom@4")


def test_router_composition_rows():
    from distributed_llms_example_tpu.analysis.composition import (
        check_composition,
        failing_combos,
    )

    bad = failing_combos(
        flags=("decode", "router"), mesh_axes={"stage": 2, "data": 4},
    )
    assert "router-pipelined" in [row.id for row in bad]
    assert not failing_combos(
        flags=("decode", "router"), mesh_axes={"data": 4, "fsdp": 2},
    )
    # the pinned combo is recognized by the lint's good table
    findings = check_composition(
        family="llama", mesh_axes={"data": 4},
        flags=("decode", "router"),
    )
    assert not [f for f in findings if f.severity == "error"]


# ---------------------------------------------------------------------------
# health machine / scheduling on deterministic fake replicas (no jax)
# ---------------------------------------------------------------------------


class FakeSession:
    """The ServeSession surface the router drives, with a deterministic
    1-token-per-step decode: ``budget`` steps per request (default 3),
    ``slots`` concurrent."""

    def __init__(self, slots=2, default_budget=3):
        self.slots = slots
        self.default_budget = default_budget
        self.requests: list[list] = []
        self.arrivals: list[float | None] = []
        self.budgets: list[int] = []
        self.labels: list = []
        self.outputs: list[list[int]] = []
        self._first: list[float | None] = []
        self.pending: list[int] = []
        self.active: dict[int, int] = {}  # local rid -> tokens emitted
        self.progress = 0
        self.frozen = False  # an ORGANIC stall: no progress, no raise
        self.finalized = False

    def submit(self, tokens, *, max_new=None, attention_mask=None, label=None,
               arrival=None):
        rid = len(self.requests)
        self.arrivals.append(arrival)
        self.requests.append(list(tokens))
        self.budgets.append(max_new or self.default_budget)
        self.labels.append(rid if label is None else label)
        self.outputs.append([])
        self._first.append(None)
        self.pending.append(rid)
        return rid

    @property
    def queue_depth(self):
        return len(self.pending)

    @property
    def active_count(self):
        return len(self.active)

    def has_work(self):
        return bool(self.pending or self.active)

    def output(self, rid):
        return self.outputs[rid]

    def first_token_wall(self, rid):
        return self._first[rid]

    def take_pending(self):
        labels = [self.labels[r] for r in self.pending]
        self.pending.clear()
        return labels

    def finalize(self):
        self.finalized = True

    def step(self):
        if self.frozen:
            return []
        finished = []
        while self.pending and len(self.active) < self.slots:
            self.active[self.pending.pop(0)] = 0
            self.progress += 1
        if self.active:
            self.progress += 1
            now = time.perf_counter()
            for rid in list(self.active):
                self.outputs[rid].append(100 + len(self.outputs[rid]))
                if self._first[rid] is None:
                    self._first[rid] = now
                self.active[rid] += 1
                if self.active[rid] >= self.budgets[rid]:
                    del self.active[rid]
                    finished.append(rid)
        return finished


class FakeEngine:
    paged = False
    prefill_batch = 2

    class serve:
        ttft_slo_ms = 0.0

    def open(self, params, *, replica=None):
        return FakeSession()


def _fake_router(n=2, **cfg) -> ReplicaRouter:
    return ReplicaRouter(
        [FakeEngine() for _ in range(n)], None,
        RouterConfig(log_every_ticks=0, **cfg),
    )


def test_stall_detector_suspect_then_dead_reprefills(capsys):
    """An organically frozen replica (no exception — only missing
    heartbeats) walks live → suspect → dead, and its requests complete
    on the survivor with retries counted and a finite request MTTR."""
    router = _fake_router(suspect_after_ticks=2, dead_after_ticks=4)
    rids = [router.submit([1, 2, 3], session=None) for _ in range(6)]
    # freeze replica 0 after its first dispatch lands
    router.tick()
    router.replicas[0].session.frozen = True
    router.run_until_drained()
    router.finalize()
    assert all(router.requests[r].done for r in rids)
    assert router.replicas[0].state == "dead"
    assert router.retries_total > 0
    assert router.last_stats["request_mttr_s"] is not None
    events = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    health = [e for e in events if e.get("event") == "replica_health"]
    seq = [(e["from"], e["to"]) for e in health if e["replica"] == 0]
    assert ("live", "suspect") in seq and ("suspect", "dead") in seq
    dead = next(e for e in health if e["to"] == "dead")
    assert dead["cause"] == "stall" and "since_tick" in dead


def test_suspect_recovers_to_live(capsys):
    """A replica that resumes progress before the dead threshold walks
    back suspect → live and keeps its work (no retry)."""
    router = _fake_router(suspect_after_ticks=1, dead_after_ticks=10)
    router.submit([1], max_new=8)
    router.tick()
    router.replicas[0].session.frozen = True
    for _ in range(3):
        router.tick()
    assert router.replicas[0].state == "suspect"
    router.replicas[0].session.frozen = False
    router.run_until_drained()
    assert router.replicas[0].state == "live"
    assert router.retries_total == 0
    events = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    assert any(
        e.get("event") == "replica_health"
        and (e["from"], e["to"]) == ("suspect", "live")
        for e in events
    )


def test_retry_exhaustion_sheds():
    """Bounded retry: when every re-dispatch lands on a dying pool, the
    request sheds with reason retries_exhausted instead of looping."""
    router = _fake_router(n=1, max_retries=1, retry_backoff_ticks=1,
                          suspect_after_ticks=1, dead_after_ticks=2)
    rid = router.submit([1, 2])
    router.tick()
    # crash the only replica twice is impossible (it stays dead) — so
    # exhaust via the failure path directly: first failure re-queues...
    router._fail_replica(router.replicas[0], cause="crash", reason="test")
    assert not router.requests[rid].shed and router.requests[rid].retries == 1
    # ...no replicas left: the driver sheds the remainder loudly
    router.run_until_drained()
    assert router.requests[rid].shed
    assert router.requests[rid].shed_reason in ("no_replicas",)
    router.finalize()
    assert router.last_stats["shed"] == 1


def test_backoff_holds_requeued_request():
    """A failure-requeued request is not re-dispatched before its
    backoff tick, and the requests behind it are not blocked."""
    router = _fake_router(retry_backoff_ticks=4, retry_backoff_cap_ticks=8)
    rid = router.submit([1, 2, 3])
    router.tick()
    router._fail_replica(router.replicas[0], cause="crash", reason="test")
    req = router.requests[rid]
    assert req.ready_tick == router.ticks + 4
    fresh = router.submit([9, 9])
    router.tick()
    # the fresh request dispatched past the held one
    assert router.requests[fresh].replica is not None
    assert req.replica is None
    router.run_until_drained()
    assert req.done and req.retries == 1


def test_admission_control_shed_and_defer():
    # policy "shed": over-bound submissions reject immediately
    router = _fake_router(max_queue=2, shed_policy="shed")
    rids = [router.submit([1]) for _ in range(5)]
    shed = [r for r in rids if router.requests[r].shed]
    assert len(shed) == 3
    assert all(router.requests[r].shed_reason == "queue_full" for r in shed)
    router.run_until_drained()
    assert all(router.requests[r].done for r in rids if r not in shed)
    # policy "defer": parked client-side, admitted as the queue drains —
    # nothing sheds, everything completes
    router2 = _fake_router(max_queue=2, shed_policy="defer")
    rids2 = [router2.submit([1]) for _ in range(5)]
    assert len(router2.deferred) == 3
    router2.run_until_drained()
    assert all(router2.requests[r].done for r in rids2)


def test_deadline_sheds_waiting_requests():
    router = _fake_router(n=1, max_queue=2, shed_policy="defer")
    ok1 = router.submit([1])
    ok2 = router.submit([1])
    # deferred behind a full queue with a 1-tick deadline: they expire
    # in the client-side buffer before they ever dispatch
    late = router.submit([1], deadline_ticks=1)
    held = router.submit([1], deadline_ticks=1)
    assert len(router.deferred) == 2
    for _ in range(3):
        router.tick()
    router.run_until_drained()
    assert router.requests[ok1].done and router.requests[ok2].done
    for r in (late, held):
        assert router.requests[r].shed
        assert router.requests[r].shed_reason == "deadline"


def test_request_storm_sheds_without_starving_real_traffic(capsys):
    """request_storm@K floods admission control; the synthetic burst
    sheds/expires while every real request still completes."""
    router = ReplicaRouter(
        [FakeEngine() for _ in range(2)], None,
        RouterConfig(
            log_every_ticks=0, max_queue=2, shed_policy="defer",
            storm_size=12, storm_deadline_ticks=2,
            chaos=parse_chaos("request_storm@2"),
        ),
    )
    rids = [router.submit([1, 2]) for _ in range(4)]
    router.run_until_drained()
    router.finalize()
    assert all(router.requests[r].done for r in rids)
    synth = [q for q in router.requests if q.synthetic]
    assert len(synth) == 12 and all(q.done or q.shed for q in synth)
    # the burst's tail expired under pressure (deadline shedding) ...
    assert sum(1 for q in synth if q.shed) > 0
    # ... while real sheds stay zero: the storm is load, not an outage
    assert router.last_stats["shed"] == 0
    assert router.last_stats["synthetic_requests"] == len(synth)


def test_drain_replica_redispatches_and_retires():
    """Graceful drain: queued work re-routes (no retry counted), live
    slots finish in place, the replica parks as drained, zero lost."""
    router = _fake_router(n=2)
    rids = [router.submit([1, 2, 3], max_new=6) for _ in range(6)]
    router.tick()
    victim = router.replicas[0]
    assert victim.session.active_count > 0
    router.drain_replica(0)
    assert victim.state == "draining"
    router.run_until_drained()
    router.finalize()
    assert victim.state == "drained"
    assert all(router.requests[r].done for r in rids)
    assert router.retries_total == 0  # drain re-dispatch is not a retry
    # in-place completions really happened on the draining replica
    assert any(router.requests[r].replica == 0 for r in rids)


def test_draining_replica_stall_is_detected():
    """Review fix: a replica that wedges MID-DRAIN must still be
    declared dead (the stall detector covers draining too) — otherwise
    its live slots never finish, never requeue, and run_until_drained
    spins forever."""
    router = _fake_router(suspect_after_ticks=1, dead_after_ticks=3)
    rids = [router.submit([1, 2], max_new=8) for _ in range(4)]
    router.tick()
    victim = router.replicas[0]
    assert victim.session.active_count > 0
    router.drain_replica(0)
    victim.session.frozen = True  # wedges while draining
    router.run_until_drained()
    router.finalize()
    assert victim.state == "dead"
    assert all(router.requests[r].done for r in rids)
    assert router.retries_total > 0  # the wedged drain's slots re-prefilled


def test_storm_retries_do_not_inflate_gated_retry_rate():
    """Review fix: synthetic storm requests retried off a dying replica
    must not count against the REAL-request denominator — the gated
    request_retry_rate is real traffic's failure retries only (the
    total, synthetic included, rides retries_total)."""
    router = ReplicaRouter(
        [FakeEngine() for _ in range(2)], None,
        RouterConfig(
            log_every_ticks=0, storm_size=10, storm_deadline_ticks=30,
            retry_backoff_ticks=1,
            chaos=parse_chaos("request_storm@1,replica_crash@3"),
        ),
    )
    rids = [router.submit([1, 2]) for _ in range(4)]
    router.run_until_drained()
    router.finalize()
    assert all(router.requests[r].done for r in rids)
    real_retries = sum(
        q.retries for q in router.requests if not q.synthetic
    )
    s = router.last_stats
    assert s["retries"] == real_retries
    assert s["request_retry_rate"] == round(real_retries / 4, 4)
    assert s["retries_total"] >= s["retries"]
    # the rate can never exceed max_retries even under storm pressure
    assert s["request_retry_rate"] <= router.cfg.max_retries


def test_router_drain_stops_admissions():
    router = _fake_router()
    ok = router.submit([1])
    router.drain()
    rejected = router.submit([2])
    assert router.requests[rejected].shed
    assert router.requests[rejected].shed_reason == "draining"
    router.run_until_drained()
    assert router.requests[ok].done


def test_session_affinity_and_failover_remap():
    """Same session key → same replica while it lives; after the mapped
    replica dies the key remaps to a survivor."""
    router = _fake_router(n=2)
    a = [router.submit([1], session="user-a") for _ in range(2)]
    b = [router.submit([1], session="user-b") for _ in range(2)]
    router.run_until_drained()
    ra = {router.requests[r].replica for r in a}
    rb = {router.requests[r].replica for r in b}
    assert len(ra) == 1 and len(rb) == 1
    mapped = router.affinity["user-a"]
    router._fail_replica(router.replicas[mapped], cause="crash", reason="t")
    c = router.submit([1], session="user-a")
    router.run_until_drained()
    assert router.requests[c].done
    assert router.requests[c].replica != mapped
    assert router.affinity["user-a"] != mapped


# ---------------------------------------------------------------------------
# real engines: the chaos acceptance + the stepwise session API
# ---------------------------------------------------------------------------


def _requests(rng, n, lo=3, hi=14):
    return [list(rng.randint(4, 120, rng.randint(lo, hi))) for _ in range(n)]


def _llama_engine(lm, W=16, L=8, slots=2):
    return ServingEngine(
        lm.module, lm.config, None,
        ServeConfig(max_slots=slots, prefill_batch=slots, max_new_tokens=L,
                    max_source_length=W, log_every_steps=0),
        is_seq2seq=False,
    )


@pytest.fixture(scope="module")
def llama_pool():
    """One tiny causal model + three engines + the single-engine oracle
    outputs, shared by the real-engine router tests (compiled programs
    are per-engine — build once)."""
    lm = load_model("llama-test")
    params = lm.init_params(0)
    rng = np.random.RandomState(7)
    reqs = _requests(rng, 10)
    engines = [_llama_engine(lm) for _ in range(3)]
    oracle = _llama_engine(lm)
    oracle_outs = oracle.generate(params, reqs)
    return lm, params, reqs, engines, oracle_outs


def test_router_crash_acceptance_bit_identical_and_report(
    llama_pool, tmp_path, capsys
):
    """THE chaos acceptance: replica_crash@K mid-decode → every in-flight
    request completes on a surviving replica, greedy tokens BIT-IDENTICAL
    to the unfailed single-engine oracle, zero requests lost; the JSONL
    stream reports the fault as injected-only (obs.report --strict rc 0)
    with finite request-level MTTR in the recovery timeline; and the
    obs_gate serving gates cut both ways."""
    from distributed_llms_example_tpu.obs.report import main as report_main

    lm, params, reqs, engines, oracle_outs = llama_pool
    out = tmp_path / "run"
    sink_mod.install_sink(sink_mod.build_sink("jsonl", str(out)))
    router = ReplicaRouter(
        engines[:2], params,
        RouterConfig(log_every_ticks=4, chaos=parse_chaos("replica_crash@4")),
    )
    outs = router.serve(reqs)
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want in zip(outs, oracle_outs):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)
    summary = router.last_stats
    assert summary["completed"] == len(reqs) and summary["shed"] == 0
    assert summary["retries"] > 0  # the crash genuinely displaced work
    assert summary["request_mttr_s"] is not None
    assert summary["replica_states"]["0"] == "dead"

    report = build_report(str(out))
    rec = report["recovery"]
    # the crash is a FAULT — and an injected one (chaos explains it)
    kinds = {f["kind"] for f in rec["faults"]}
    assert "replica_crash" in kinds
    assert rec["organic_faults"] == []
    serving = rec["serving"]
    assert serving["replicas_lost"] == 1
    assert serving["retries"] == summary["retries"]
    assert serving["request_mttr_s"] == summary["request_mttr_s"]
    assert serving["request_retry_rate"] == summary["request_retry_rate"]
    md = render_markdown(report)
    assert "replica 0" in md and "request MTTR" in md
    # strict: green on the injected-only run, with the serving gates
    capsys.readouterr()
    assert report_main([str(out), "--strict", "--json"]) == 0
    assert report_main([
        str(out), "--strict", "--json",
        "--max-request-retry-rate", "0.9",
        "--min-serve-goodput-frac", "0.9",
    ]) == 0
    # any retry over a zero ceiling fails; so does a goodput floor above 1
    assert report_main([
        str(out), "--strict", "--json", "--max-request-retry-rate", "0",
    ]) == 1
    capsys.readouterr()


def test_router_organic_crash_fails_strict(llama_pool, tmp_path, capsys):
    """An ORGANIC replica death (an exception out of step with no chaos
    injection explaining it) turns obs.report --strict red — the
    injected-vs-organic split, serving edition."""
    from distributed_llms_example_tpu.obs.report import main as report_main

    lm, params, reqs, engines, oracle_outs = llama_pool
    out = tmp_path / "run"
    sink_mod.install_sink(sink_mod.build_sink("jsonl", str(out)))
    router = ReplicaRouter(engines[:2], params, RouterConfig(log_every_ticks=0))
    for r in reqs:
        router.submit(r)
    router.tick()
    # an organic failure: the replica's step raises out of nowhere
    sess = router.replicas[0].session
    sess.step = lambda: (_ for _ in ()).throw(RuntimeError("device lost"))
    router.run_until_drained()
    router.finalize()
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want in zip(
        [list(router.requests[i].out) for i in range(len(reqs))], oracle_outs
    ):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)
    rec = build_report(str(out))["recovery"]
    organic = [f for f in rec["organic_faults"]]
    assert any(f["kind"] == "replica_crash" for f in organic)
    capsys.readouterr()
    assert report_main([str(out), "--strict", "--json"]) == 1
    capsys.readouterr()


def test_router_statelessness_drain_leaves_nothing(llama_pool, tmp_path):
    """Graceful drain checkpoints NOTHING because there is nothing to
    checkpoint: no file appears anywhere, and a fresh router rebuilt
    from just the params + request stream reproduces the identical
    output — serving state is derived, not owned."""
    lm, params, reqs, engines, oracle_outs = llama_pool
    probe = tmp_path / "probe"
    probe.mkdir()
    cwd = os.getcwd()
    os.chdir(probe)
    try:
        router = ReplicaRouter(engines[:2], params, RouterConfig(log_every_ticks=0))
        for r in reqs:
            router.submit(r)
        router.tick()
        router.drain_replica(0)
        router.run_until_drained()
        router.finalize()
        outs1 = [list(router.requests[i].out) for i in range(len(reqs))]
    finally:
        os.chdir(cwd)
    assert os.listdir(probe) == []  # drained with zero persisted state
    assert router.replicas[0].state in ("drained", "live", "draining")
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want in zip(outs1, oracle_outs):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)
    # rebuild from scratch: same stream, same tokens (statelessness)
    router2 = ReplicaRouter(engines[:2], params, RouterConfig(log_every_ticks=0))
    outs2 = router2.serve(reqs)
    assert outs2 == outs1


def test_serve_session_incremental_equals_batch(llama_pool):
    """The stepwise session API: submitting mid-flight (the router's
    arrival pattern) produces the same per-request tokens as the batch
    generate over the same engine."""
    lm, params, reqs, engines, oracle_outs = llama_pool
    eng = engines[2]
    sess = eng.open(params)
    first = [sess.submit(r) for r in reqs[:4]]
    for _ in range(3):
        sess.step()
    late = [sess.submit(r) for r in reqs[4:]]
    while sess.has_work():
        sess.step()
    stats = sess.finalize()
    assert stats.sequences == len(reqs)
    got = [sess.output(r) for r in first + late]
    assert got == oracle_outs
    # take_pending on a fresh session empties the queue, labels intact
    sess2 = eng.open(params)
    sess2.submit(reqs[0], label=41)
    sess2.submit(reqs[1], label=42)
    assert sess2.take_pending() == [41, 42]
    assert not sess2.has_work()
    sess2.finalize()


# ---------------------------------------------------------------------------
# crash-safe product output (satellite: serve JSONL through the sink
# discipline) — kill -9 leaves no torn lines
# ---------------------------------------------------------------------------


def test_product_jsonl_writer_survives_kill9(tmp_path):
    """The serve CLI's output writer: one os-level write per line.  A
    process SIGKILLed mid-stream leaves a file where EVERY line parses —
    records can be missing (never flushed), never torn or interleaved —
    mirroring the PR 3 sink durability test."""
    out = tmp_path / "serve-out.jsonl"
    # records over the ~8 KiB TextIOWrapper chunk: the raw-fd writer
    # must land even those in one write, so no line can tear mid-record
    script = textwrap.dedent(f"""
        import os, signal
        from distributed_llms_example_tpu.obs.sink import ProductJsonlWriter

        w = ProductJsonlWriter({str(out)!r})
        for i in range(200):
            w.write({{"prompt": "p" * 64, "output": "o" * 20000, "tokens": i}})
        os.kill(os.getpid(), signal.SIGKILL)  # kill -9: no close, no atexit
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    lines = out.read_text().splitlines()
    assert len(lines) == 200  # every single-syscall write reached the OS
    for line in lines:
        rec = json.loads(line)  # no torn line anywhere
        assert {"prompt", "output", "tokens"} <= set(rec)
        assert len(rec["output"]) == 20000


# ---------------------------------------------------------------------------
# report: serving gates fail on MISSING measurements
# ---------------------------------------------------------------------------


def test_report_serving_counts_exclude_synthetic_and_drain(tmp_path):
    """Review fixes: the serving report's retries/shed counts track REAL
    traffic like router_summary does — drain re-dispatches and synthetic
    storm events ride the *_total/redispatch fields instead of reading
    as real-request loss."""
    from distributed_llms_example_tpu.obs.sink import SCHEMA_VERSION

    obs = tmp_path / "obs"
    obs.mkdir()
    recs = [
        {"event": "serve_retry", "request": 1, "retries": 1, "tick": 4,
         "reason": "crash", "synthetic": False},
        {"event": "serve_retry", "request": 2, "retries": 0, "tick": 5,
         "reason": "drain", "synthetic": False},
        {"event": "serve_retry", "request": 9, "retries": 1, "tick": 6,
         "reason": "crash", "synthetic": True},
        {"event": "serve_shed", "request": 8, "reason": "deadline",
         "tick": 9, "synthetic": True},
        {"event": "serve_shed", "request": 3, "reason": "retries_exhausted",
         "tick": 9, "synthetic": False},
    ]
    (obs / "metrics-p000.jsonl").write_text(
        "\n".join(
            json.dumps({"schema_version": SCHEMA_VERSION, **r}) for r in recs
        ) + "\n"
    )
    serving = build_report(str(tmp_path))["recovery"]["serving"]
    assert serving["retries"] == 1  # crash retry of real traffic only
    assert serving["redispatches"] == 3
    assert serving["shed"] == 1  # the real shed
    assert serving["shed_total"] == 2


def test_serving_gates_fail_without_router_summary(tmp_path, capsys):
    from distributed_llms_example_tpu.obs.report import main as report_main
    from distributed_llms_example_tpu.obs.sink import SCHEMA_VERSION

    obs = tmp_path / "obs"
    obs.mkdir()
    (obs / "metrics-p000.jsonl").write_text(
        json.dumps({"schema_version": SCHEMA_VERSION, "event": "metric",
                    "step": 1, "loss": 1.0}) + "\n"
    )
    capsys.readouterr()
    assert report_main([str(tmp_path), "--strict", "--json"]) == 0
    assert report_main([
        str(tmp_path), "--strict", "--json", "--max-request-retry-rate", "1",
    ]) == 1
    assert report_main([
        str(tmp_path), "--strict", "--json", "--min-serve-goodput-frac", "0.5",
    ]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# serve-router CLI e2e (slow: model load + N compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_router_cli_end_to_end(tmp_path):
    from distributed_llms_example_tpu.launch.cli import serve_router_main

    prompts = tmp_path / "prompts.json"
    prompts.write_text(json.dumps([
        {"dialogue": f"prompt number {i} with some words", "summary": "x"}
        for i in range(6)
    ]))
    out = tmp_path / "out.jsonl"
    rc = serve_router_main([
        "--model-ckpt", "t5-test",
        "--prompts-file", str(prompts),
        "--output-file", str(out),
        "--replicas", "2",
        "--max-slots", "8", "--prefill-batch", "8",
        "--max-new-tokens", "8", "--max-source-length", "32",
        "--compute-dtype", "float32", "--log-every-steps", "0",
        "--chaos", "replica_crash@3",
    ])
    assert rc == 0
    recs = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(recs) == 6
    assert all({"prompt", "output", "tokens"} <= set(r) for r in recs)
    # nothing lost to the crash: no record carries a shed marker (a
    # tokens==0 row is legal — random-init t5 can emit EOS immediately)
    assert all("shed" not in r for r in recs)


# ---------------------------------------------------------------------------
# prefix cache across the replica tier
# ---------------------------------------------------------------------------


def test_router_crash_drops_warm_set_bit_identical(llama_pool):
    """Replica-kill leg of the prefix-cache contract: session-keyed
    multi-turn traffic over prefix-enabled replicas, replica_crash
    mid-run — every request still completes with tokens bit-identical
    to the cold single-engine oracle, the DEAD replica's warm set is
    dropped with it (its device pool is gone, so its chains must not
    stay matchable) with zero leaked blocks, and the router summary
    still carries the surviving tier's reuse ledger."""
    lm, params, _, _, _ = llama_pool
    rng = np.random.RandomState(41)
    sys_toks = [int(t) for t in rng.randint(4, 120, 8)]
    reqs, keys = [], []
    for i in range(10):
        reqs.append(
            sys_toks + [int(t) for t in rng.randint(4, 120, rng.randint(2, 8))]
        )
        keys.append(f"session-{i % 3}")
    oracle = _llama_engine(lm).generate(params, reqs)

    def prefix_engine():
        return ServingEngine(
            lm.module, lm.config, None,
            ServeConfig(
                max_slots=2, prefill_batch=2, max_new_tokens=8,
                max_source_length=16, log_every_steps=0,
                paged_kv=True, kv_block_size=8, pool_blocks=24,
                prefix_cache=True, prefix_cache_budget_gib=0.25,
            ),
            is_seq2seq=False,
        )

    router = ReplicaRouter(
        [prefix_engine(), prefix_engine()], params,
        RouterConfig(log_every_ticks=0, chaos=parse_chaos("replica_crash@4")),
    )
    outs = router.serve(reqs, sessions=keys)
    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    for got, want in zip(outs, oracle):
        assert trim_eos(got, eos, pad) == trim_eos(want, eos, pad)
    summary = router.last_stats
    assert summary["completed"] == len(reqs) and summary["shed"] == 0
    dead = [r for r in router.replicas if r.state == "dead"]
    assert len(dead) == 1
    # the dead replica's warm chains died with it — and nothing leaked
    assert dead[0].engine.pool.blocks_warm == 0
    assert dead[0].engine.pool.blocks_in_use == 0
    # the survivor kept (re-)building the shared block: the tier-level
    # ledger reports real reuse despite the mid-run warm drop
    assert summary["prefix_lookups"] > 0
    assert summary["prefix_hits"] > 0
    assert 0.0 < summary["prefix_hit_rate"] <= 1.0
    assert summary["prefill_tokens_saved_frac"] > 0.0
    # the survivor's retained set is still live-matchable for a follow-up
    survivor = next(r for r in router.replicas if r.state != "dead")
    assert survivor.engine.pool.blocks_warm > 0


def test_prefix_report_section_and_gate(llama_pool, tmp_path, capsys):
    """The report's prefix rollup + the strict gate cutting both ways:
    a prefix-enabled run renders the '## Prefix cache' section and
    passes a floor its hit rate meets, fails one above it — and a run
    with NO prefix measurement fails the gate outright (missing
    measurement is never a pass)."""
    from distributed_llms_example_tpu.obs.report import main as report_main
    from scripts.obs_gate import main as gate_main

    lm, params, _, _, _ = llama_pool
    rng = np.random.RandomState(43)
    sys_toks = [int(t) for t in rng.randint(4, 120, 8)]
    reqs = [
        sys_toks + [int(t) for t in rng.randint(4, 120, rng.randint(2, 8))]
        for _ in range(6)
    ]
    eng = ServingEngine(
        lm.module, lm.config, None,
        ServeConfig(
            max_slots=2, prefill_batch=2, max_new_tokens=8,
            max_source_length=16, log_every_steps=0,
            paged_kv=True, kv_block_size=8, pool_blocks=24,
            prefix_cache=True, prefix_cache_budget_gib=0.25,
        ),
        is_seq2seq=False,
    )
    out = tmp_path / "run"
    sink_mod.install_sink(sink_mod.build_sink("jsonl", str(out)))
    eng.generate(params, reqs)
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    report = build_report(str(out))
    px = report["prefix"]
    assert px is not None and px["scope"] == "engine"
    assert px["hit_rate"] == pytest.approx(
        eng.last_stats.prefix_hits / max(eng.last_stats.prefix_lookups, 1),
        abs=1e-4,
    )
    assert px["prefill_tokens_saved"] == eng.last_stats.prefill_tokens_saved
    md = render_markdown(report)
    assert "## Prefix cache" in md and "prefill tokens saved" in md
    capsys.readouterr()
    # the gate cuts both ways around the measured rate
    rate = px["hit_rate"]
    assert report_main([
        str(out), "--strict", "--json",
        "--min-prefix-hit-rate", str(rate - 0.01),
    ]) == 0
    assert report_main([
        str(out), "--strict", "--json",
        "--min-prefix-hit-rate", str(rate + 0.01),
    ]) == 1
    # ...and forwards through the pinned-flags wrapper
    assert gate_main([
        str(out), "--min-dispatch-efficiency", "0",
        "--min-prefix-hit-rate", str(rate - 0.01),
    ]) == 0
    # a run with no prefix-enabled summary: the gate fails as missing
    cold = tmp_path / "cold"
    sink_mod.install_sink(sink_mod.build_sink("jsonl", str(cold)))
    ServingEngine(
        lm.module, lm.config, None,
        ServeConfig(max_slots=2, prefill_batch=2, max_new_tokens=8,
                    max_source_length=16, log_every_steps=0),
        is_seq2seq=False,
    ).generate(params, reqs[:2])
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    assert build_report(str(cold))["prefix"] is None
    assert report_main([
        str(cold), "--strict", "--json", "--min-prefix-hit-rate", "0.1",
    ]) == 1
    capsys.readouterr()
