"""LLaMA numerical parity vs HF PyTorch on shared random weights (incl. GQA)."""

import numpy as np
import pytest

from distributed_llms_example_tpu.models.convert import convert_llama_state_dict
from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _make_pair(kv_heads):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=kv_heads,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        attention_dropout=0.0,
        pad_token_id=0,
        bos_token_id=1,
        eos_token_id=2,
        attn_implementation="eager",
    )
    torch.manual_seed(11)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=kv_heads, max_position_embeddings=64,
    )
    model = LlamaForCausalLM(cfg)
    params = convert_llama_state_dict(hf_model.state_dict())
    return hf_model, model, cfg, params


@pytest.mark.parametrize("kv_heads", [4, 2], ids=["mha", "gqa"])
def test_forward_parity(kv_heads):
    hf_model, model, cfg, params = _make_pair(kv_heads)
    rng = np.random.RandomState(0)
    ids = rng.randint(3, 128, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[1, -4:] = 0
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(model.apply({"params": params}, ids, mask))
    # padded rows attend differently in HF (left-pad convention); compare
    # positions where every later position is valid — row 0 fully, row 1 on
    # its valid prefix
    np.testing.assert_allclose(got[0], ref[0], atol=3e-4, rtol=2e-3)
    np.testing.assert_allclose(got[1, :8], ref[1, :8], atol=3e-4, rtol=2e-3)


@pytest.mark.slow  # ~7s cached-decode compile: slow tier
def test_cached_decode_matches_full():
    import jax
    import jax.numpy as jnp

    _, model, cfg, params = _make_pair(2)
    rng = np.random.RandomState(1)
    ids = rng.randint(3, 128, (2, 8)).astype(np.int32)
    full = np.asarray(model.apply({"params": params}, ids))

    L = ids.shape[1]
    shapes = jax.eval_shape(
        lambda p: model.init(jax.random.PRNGKey(0), jnp.zeros((2, L), jnp.int32), use_cache=True),
        params,
    )
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])
    outs = []
    for t in range(L):
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            ids[:, t : t + 1],
            use_cache=True,
            mutable=["cache"],
        )
        cache = mut["cache"]
        outs.append(np.asarray(logits[:, 0]))
    stepwise = np.stack(outs, axis=1)
    np.testing.assert_allclose(stepwise, full, atol=3e-4, rtol=2e-3)
