"""Optimizer-TRAJECTORY parity vs a minimal torch training loop.

BASELINE.md's quality target (ROUGE-L parity with the reference's torch
run) needs real weights, which this environment cannot download.  The
strongest offline stand-in: on SHARED tiny random weights, run N steps of
the full optimizer semantics — AdamW 5e-5 (b1 .9, b2 .999, eps 1e-8),
linear warmup+decay schedule, global-norm clip 1.0, the no-decay split —
here and in a hand-written torch loop (the reference's loop,
reference train-accelerator.py:174-205, minus its dead knobs), on the
SAME batches, and pin the loss curves together.  Single-step logit parity
(test_bart_parity) catches model bugs; this catches optimizer/schedule/
clipping semantics drift that would silently change training outcomes.
"""

import jax
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.models.bart import BartConfig, BartForConditionalGeneration
from distributed_llms_example_tpu.models.convert import convert_bart_state_dict
from distributed_llms_example_tpu.models.t5 import shift_right
from distributed_llms_example_tpu.train.optim import make_optimizer
from distributed_llms_example_tpu.train.step import create_train_state, make_train_step

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

N_STEPS = 20
LR, WD, WARMUP, CLIP = 5e-5, 0.01, 3, 1.0
LABEL_PAD = -100


def _pair():
    hf_cfg = transformers.BartConfig(
        vocab_size=128, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=96, decoder_ffn_dim=96, max_position_embeddings=64,
        dropout=0.0, attention_dropout=0.0, activation_dropout=0.0,
        scale_embedding=True, pad_token_id=1, bos_token_id=0, eos_token_id=2,
        decoder_start_token_id=2, forced_bos_token_id=0,
    )
    torch.manual_seed(7)
    hf_model = transformers.BartForConditionalGeneration(hf_cfg)
    hf_model.train()  # dropout rates are all 0 → deterministic anyway
    cfg = BartConfig(
        vocab_size=128, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=96, decoder_ffn_dim=96, max_position_embeddings=64,
        dropout_rate=0.0, scale_embedding=True, forced_bos_token_id=0,
    )
    model = BartForConditionalGeneration(cfg)
    params = convert_bart_state_dict(hf_model.state_dict())
    return hf_model, model, cfg, params


def _batches():
    rng = np.random.RandomState(42)
    out = []
    for _ in range(N_STEPS):
        ids = rng.randint(4, 128, (8, 12)).astype(np.int32)
        mask = np.ones((8, 12), np.int32)
        mask[0, -4:] = 0
        labels = rng.randint(4, 128, (8, 7)).astype(np.int32)
        labels[:, -2:] = LABEL_PAD
        out.append({"input_ids": ids, "attention_mask": mask, "labels": labels})
    return out


def _torch_losses(hf_model) -> list[float]:
    """The reference loop: param split, AdamW, linear schedule, clip."""
    decay, no_decay = [], []
    for p in hf_model.parameters():
        (decay if p.ndim >= 2 else no_decay).append(p)
    opt = torch.optim.AdamW(
        [{"params": decay, "weight_decay": WD}, {"params": no_decay, "weight_decay": 0.0}],
        lr=LR, betas=(0.9, 0.999), eps=1e-8,
    )
    sched = transformers.get_linear_schedule_with_warmup(opt, WARMUP, N_STEPS)
    ce = torch.nn.CrossEntropyLoss(ignore_index=LABEL_PAD)
    losses = []
    for batch in _batches():
        dec_in = np.asarray(shift_right(batch["labels"], 2, 1))
        out = hf_model(
            input_ids=torch.tensor(batch["input_ids"], dtype=torch.long),
            attention_mask=torch.tensor(batch["attention_mask"], dtype=torch.long),
            decoder_input_ids=torch.tensor(dec_in, dtype=torch.long),
        )
        loss = ce(
            out.logits.reshape(-1, out.logits.shape[-1]),
            torch.tensor(batch["labels"], dtype=torch.long).reshape(-1),
        )
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(hf_model.parameters(), CLIP)
        opt.step()
        sched.step()
        losses.append(float(loss.detach()))
    return losses


def _ours_losses(model, cfg, params) -> list[float]:
    mesh = build_mesh(MeshConfig(data=-1))
    tx, schedule = make_optimizer(
        learning_rate=LR, weight_decay=WD, warmup_steps=WARMUP,
        total_steps=N_STEPS, max_grad_norm=CLIP,
    )
    state = create_train_state(jax.tree.map(np.asarray, params), tx)
    build = make_train_step(
        model, cfg, tx, schedule, mesh, is_seq2seq=True, sequence_sharded=False, donate=False,
    )
    step_fn, state_sh = build(state)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_sh)
    from distributed_llms_example_tpu.train.step import put_batch

    losses = []
    for batch in _batches():
        state, metrics = step_fn(state, put_batch(batch, mesh))
        losses.append(float(metrics["loss"]))
    return losses


@pytest.mark.slow  # ~24s twin-compile trajectory: slow tier (the fast
# tier keeps the single-step optimizer parity pins)
def test_twenty_step_loss_curve_parity():
    hf_model, model, cfg, params = _pair()
    ours = _ours_losses(model, cfg, params)
    ref = _torch_losses(hf_model)
    # step 0 is pure forward parity; later steps compound optimizer updates
    # (fp32 everywhere, so agreement should be tight through 20 steps)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-4)
    # the curve must actually be a trajectory, not a flat line: training
    # happened (losses move) and both sides agree step by step
    assert abs(ours[0] - ours[-1]) > 1e-3
