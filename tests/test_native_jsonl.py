"""Native C++ JSONL loader: build, parse, parity with the Python path.

The loader compiles on demand with g++ (present in CI and dev images); if
the toolchain were missing, ``native.available()`` gates every use and the
Python fallback keeps identical semantics — the first test asserts which
world we're in instead of skipping silently.
"""

import json

import pytest

from distributed_llms_example_tpu import native
from distributed_llms_example_tpu.data.dataset import load_json_records

RECORDS = [
    {"dialogue": "plain ascii", "summary": "ok"},
    {"dialogue": 'quotes " and \\ backslash / slash', "summary": "\b\f\n\r\t controls"},
    {"dialogue": "unicode café 日本語", "summary": "astral \U0001f600 emoji"},
    {"dialogue": "numbers", "summary": "x", "id": 17, "score": -3.25e2, "ok": True, "meta": None},
    {"dialogue": "nested", "summary": "y", "tags": ["a", "b"], "extra": {"k": [1, 2]}},
    {},
]


@pytest.fixture(scope="module")
def jsonl_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("jsonl") / "data.jsonl"
    with open(p, "w", encoding="utf-8") as f:
        for r in RECORDS:
            f.write(json.dumps(r) + "\n")
    return str(p)


def test_native_loader_builds():
    assert native.available(), f"native loader failed to build: {native.build_error()}"


def test_native_matches_python(jsonl_file):
    recs = native.load_jsonl(jsonl_file)
    assert len(recs) == len(RECORDS)
    for got, want in zip(recs, RECORDS):
        assert got == want


def test_escapes_round_trip(tmp_path):
    # ensure the C++ unescaper (not Python's) handles every escape form:
    # write escapes explicitly, including \u-encoded surrogate pairs
    p = tmp_path / "esc.jsonl"
    p.write_text(
        '{"a": "tab\\there", "b": "\\u0041\\u00e9\\u65e5", "c": "\\ud83d\\ude00", "d": "sl\\/ash"}\n',
        encoding="utf-8",
    )
    (rec,) = native.load_jsonl(str(p))
    assert rec == {"a": "tab\there", "b": "Aé日", "c": "\U0001f600", "d": "sl/ash"}


def test_blank_lines_and_missing_trailing_newline(tmp_path):
    p = tmp_path / "gaps.jsonl"
    p.write_text('{"a": "1"}\n\n  \n{"a": "2"}', encoding="utf-8")
    recs = native.load_jsonl(str(p))
    assert [r["a"] for r in recs] == ["1", "2"]


def test_lone_surrogates_rejected_at_parse(tmp_path):
    """Lone \\u surrogates (either half) must fail at LOAD time — past
    load, the Python fallback can no longer engage and the bad bytes would
    surface as UnicodeDecodeError mid-training."""
    for esc in ("\\ud800", "\\udc00"):
        p = tmp_path / "lone.jsonl"
        p.write_text('{"a": "bad %s"}\n' % esc, encoding="utf-8")
        with pytest.raises(ValueError, match="surrogate"):
            native.load_jsonl(str(p))


def test_invalid_utf8_rejected_at_parse(tmp_path):
    """A stray non-UTF-8 byte in a string value must fail at LOAD time
    (clean fallback window), not as UnicodeDecodeError at access time."""
    p = tmp_path / "latin1.jsonl"
    p.write_bytes(b'{"a": "caf\xe9"}\n')  # latin-1 e-acute, invalid UTF-8
    with pytest.raises(ValueError, match="UTF-8"):
        native.load_jsonl(str(p))


def test_negative_indexing_matches_list(jsonl_file):
    recs = native.load_jsonl(jsonl_file)
    assert recs[-1] == RECORDS[-1]
    with pytest.raises(IndexError):
        recs[-len(RECORDS) - 1]


def test_malformed_reports_line(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"a": "ok"}\n{"a": nope}\n', encoding="utf-8")
    with pytest.raises(ValueError, match="line 2"):
        native.load_jsonl(str(p))


def test_load_json_records_routes_jsonl_natively(jsonl_file):
    recs = load_json_records(jsonl_file)
    if native.available():
        assert isinstance(recs, native.JsonlRecords)
    assert list(recs) == RECORDS


def test_load_json_records_python_fallback_parity(jsonl_file, monkeypatch):
    monkeypatch.setenv("DLLM_NATIVE_JSONL", "0")
    recs = load_json_records(jsonl_file)
    assert not isinstance(recs, native.JsonlRecords)
    assert list(recs) == RECORDS


def test_data_wrapper_still_works(tmp_path):
    # single {"data": [...]} object is not JSONL; the native parser must
    # reject it cleanly and the Python path must take over
    p = tmp_path / "wrap.json"
    p.write_text(json.dumps({"data": [{"dialogue": "d", "summary": "s"}]}, indent=2))
    recs = load_json_records(str(p))
    assert list(recs) == [{"dialogue": "d", "summary": "s"}]


def test_dataset_over_native_records(jsonl_file):
    """The lazy dataset consumes the lazy native sequence directly."""
    from distributed_llms_example_tpu.data.dataset import SummarizationDataset
    from distributed_llms_example_tpu.data.tokenizer import get_tokenizer

    recs = load_json_records(jsonl_file)
    ds = SummarizationDataset(recs, get_tokenizer("byte", ""))
    ex = ds[0]
    assert ex.input_ids and ex.labels
