"""Test harness: an 8-device virtual CPU mesh.

The reference has no tests at all (SURVEY.md §4); its multi-node path is
untestable without a cluster.  JAX removes that excuse:
``--xla_force_host_platform_device_count=8`` gives every test a faithful
8-device SPMD environment on CPU, so sharding, collectives, and the full
train step are exercised in CI exactly as they run on a v5e-8 slice.

The environment (cpu platform, 8 virtual devices) is guaranteed by
``dllm_test_bootstrap.py`` at the repo root, loaded pre-capture through
``addopts = -p dllm_test_bootstrap`` — see that module for why a plain
env-var set here would be too late.
"""

import jax

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh

    return build_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))


@pytest.fixture(scope="session")
def dp_mesh():
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh

    return build_mesh(MeshConfig(data=-1))


def _tiny_llama(layers: int):
    import jax.numpy as jnp

    from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=layers, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    module = LlamaForCausalLM(cfg)
    params = jax.device_get(
        module.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    )
    return cfg, module, params


@pytest.fixture()
def tiny_llama4():
    """4-layer tiny LLaMA (llama-test is 2 layers; stage=4 needs 4)."""
    return _tiny_llama(4)


@pytest.fixture()
def tiny_llama8():
    """8 tiny layers: depth for stage=4 × v=2 interleaved chunks."""
    return _tiny_llama(8)
