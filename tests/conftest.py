"""Test harness: an 8-device virtual CPU mesh.

The reference has no tests at all (SURVEY.md §4); its multi-node path is
untestable without a cluster.  JAX removes that excuse:
``--xla_force_host_platform_device_count=8`` gives every test a faithful
8-device SPMD environment on CPU, so sharding, collectives, and the full
train step are exercised in CI exactly as they run on a v5e-8 slice.

The environment (cpu platform, 8 virtual devices) is guaranteed by
``dllm_test_bootstrap.py`` at the repo root, loaded pre-capture through
``addopts = -p dllm_test_bootstrap`` — see that module for why a plain
env-var set here would be too late.
"""

import jax

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh

    return build_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))


@pytest.fixture(scope="session")
def dp_mesh():
    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh

    return build_mesh(MeshConfig(data=-1))
