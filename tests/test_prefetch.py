"""Input-pipeline overlap: Prefetcher semantics + lazy tokenization.

VERDICT round-1 item 5: the constructor tokenized the whole corpus on every
host and each batch was assembled on the critical path.  These tests pin
the new behavior: zero tokenizer calls at construction, memoized access,
prefetch preserving order/exceptions, and actual producer/consumer overlap.
"""

import time

import pytest

from distributed_llms_example_tpu.data.dataset import CausalLMDataset, SummarizationDataset
from distributed_llms_example_tpu.data.prefetch import Prefetcher
from distributed_llms_example_tpu.data.tokenizer import get_tokenizer


class CountingTokenizer:
    """Wraps the byte tokenizer, counting role-method encode calls (the
    entry points datasets actually use)."""

    def __init__(self):
        self._tok = get_tokenizer("byte", "")
        self.encode_calls = 0

    def _count(self, method, *args):
        self.encode_calls += 1
        return getattr(self._tok, method)(*args)

    def encode_source(self, text, max_length):
        return self._count("encode_source", text, max_length)

    def encode_target(self, text, max_length):
        return self._count("encode_target", text, max_length)

    def encode_prompt(self, text, max_length):
        return self._count("encode_prompt", text, max_length)

    def encode_continuation(self, text, max_length):
        return self._count("encode_continuation", text, max_length)

    def __getattr__(self, name):
        return getattr(self._tok, name)


RECORDS = [{"dialogue": f"dialogue number {i}", "summary": f"sum {i}"} for i in range(16)]


def test_dataset_tokenizes_lazily_and_memoizes():
    tok = CountingTokenizer()
    ds = SummarizationDataset(RECORDS, tok)
    assert tok.encode_calls == 0, "construction must not tokenize the corpus"
    ex = ds[3]
    assert tok.encode_calls == 2  # source + target
    assert ds[3] is ex, "second access must hit the memo, not re-tokenize"
    assert tok.encode_calls == 2
    assert ex.input_ids[-1] == tok.eos_id


def test_causal_dataset_tokenizes_lazily():
    tok = CountingTokenizer()
    ds = CausalLMDataset(RECORDS, tok, max_length=64)
    assert tok.encode_calls == 0
    ex = ds[0]
    assert tok.encode_calls == 2
    assert ex.labels[: len(ex.prompt_ids)] == [-100] * len(ex.prompt_ids)
    ds[0]
    assert tok.encode_calls == 2


def test_prefetcher_preserves_order():
    with Prefetcher(iter(range(100)), depth=3) as pf:
        assert list(pf) == list(range(100))


def test_prefetcher_propagates_exception():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("producer blew up")

    pf = Prefetcher(gen(), depth=2)
    assert next(pf) == 1
    assert next(pf) == 2
    with pytest.raises(RuntimeError, match="producer blew up"):
        next(pf)


def test_prefetcher_overlaps_producer_and_consumer():
    """With production and consumption each taking ~t per item, overlap
    means total wall time ≈ max(producer, consumer), not their sum."""
    n, t = 10, 0.03

    def slow_producer():
        for i in range(n):
            time.sleep(t)
            yield i

    start = time.perf_counter()
    for _ in Prefetcher(slow_producer(), depth=2):
        time.sleep(t)  # consumer work
    elapsed = time.perf_counter() - start
    serial = 2 * n * t
    # generous margin for CI jitter: must still clearly beat serial execution
    assert elapsed < serial * 0.8, f"no overlap: {elapsed:.3f}s vs serial {serial:.3f}s"


def test_prefetcher_close_stops_producer():
    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    pf = Prefetcher(gen(), depth=2)
    assert next(pf) == 0
    pf.close()
    time.sleep(0.2)
    n_after_close = len(produced)
    time.sleep(0.2)
    assert len(produced) == n_after_close, "producer kept running after close()"
    assert n_after_close < 1000


def test_prefetch_stats_locate_the_blocking_side():
    """The span-based answer to BENCH_r05's 'prefetch2 ≈ prefetch0'
    puzzle, as a regression test: stats() must show a large consumer wait
    when the producer is the bottleneck (prefetch cannot hide it) and a
    near-zero wait when the consumer is (the device-bound trainer loop —
    depth buys nothing because there is nothing to hide)."""
    import time as _time

    def slow_producer():
        for i in range(10):
            _time.sleep(0.02)
            yield i

    pf = Prefetcher(slow_producer(), depth=2)
    assert list(pf) == list(range(10))
    s_producer_bound = pf.stats()
    assert s_producer_bound["items"] == 10
    # ~0.2 s of production blocked the consumer
    assert s_producer_bound["consumer_wait_s"] > 0.1

    pf = Prefetcher(iter(range(10)), depth=2)
    got = []
    for x in pf:
        _time.sleep(0.005)  # consumer-bound: producer always ahead
        got.append(x)
    assert got == list(range(10))
    s = pf.stats()
    assert s["items"] == 10
    # relative, not an absolute wall-clock bound (a scheduler stall on a
    # loaded runner must not flake this): the consumer-bound wait is a
    # small fraction of the producer-bound one
    assert s["consumer_wait_s"] < s_producer_bound["consumer_wait_s"] / 2
