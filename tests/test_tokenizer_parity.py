"""Special-token layout parity against real HF tokenizers.

The reference feeds `tokenizer(text, max_length=…, truncation=True)` /
`text_target=` straight into training (reference train-accelerator.py:114-133),
so each family's pretraining layout (BART `<s>…</s>`, T5 `…</s>`, LLaMA
`<s>…`) arrives via the tokenizer's post-processor.  These tests build the
same three layouts as REAL `transformers` fast tokenizers from local
fixtures (no egress: trained in-process, saved to a tmp dir, reloaded via
``AutoTokenizer.from_pretrained(local_files_only=True)``) and assert the
framework's datasets produce byte-identical ids to the direct
`AutoTokenizer.__call__` recipe.
"""

import pytest

from distributed_llms_example_tpu.data.dataset import CausalLMDataset, SummarizationDataset
from distributed_llms_example_tpu.data.tokenizer import HFTokenizer

TEXTS = [
    "hello world the story of a summary",
    "the story hello hello world",
]
RECORDS = [{"dialogue": t, "summary": "summary of the story"} for t in TEXTS]


def _train_base(special_tokens):
    """A tiny byte-level BPE trained on the fixture corpus in-process."""
    from tokenizers import Tokenizer as TK, models, pre_tokenizers
    from tokenizers.trainers import BpeTrainer

    tok = TK(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    trainer = BpeTrainer(special_tokens=special_tokens, vocab_size=300)
    tok.train_from_iterator([r["dialogue"] + " " + r["summary"] for r in RECORDS] * 5, trainer)
    return tok


def _save_and_load(tmp_path, tok, **special_kw):
    from transformers import AutoTokenizer, PreTrainedTokenizerFast

    fast = PreTrainedTokenizerFast(tokenizer_object=tok, **special_kw)
    d = str(tmp_path / "tok")
    fast.save_pretrained(d)
    return AutoTokenizer.from_pretrained(d, local_files_only=True), d


def _bart_like(tmp_path):
    """BART layout: <s> … </s> on both source and target."""
    from tokenizers import processors

    tok = _train_base(["<s>", "<pad>", "</s>", "<unk>"])
    bos, eos = tok.token_to_id("<s>"), tok.token_to_id("</s>")
    tok.post_processor = processors.TemplateProcessing(
        single="<s> $A </s>", pair="<s> $A </s> $B </s>",
        special_tokens=[("<s>", bos), ("</s>", eos)],
    )
    return _save_and_load(
        tmp_path, tok,
        bos_token="<s>", eos_token="</s>", pad_token="<pad>", unk_token="<unk>",
    )


def _t5_like(tmp_path):
    """T5 layout: … </s>, no BOS anywhere."""
    from tokenizers import processors

    tok = _train_base(["<pad>", "</s>", "<unk>"])
    eos = tok.token_to_id("</s>")
    tok.post_processor = processors.TemplateProcessing(
        single="$A </s>", pair="$A </s> $B </s>", special_tokens=[("</s>", eos)],
    )
    return _save_and_load(
        tmp_path, tok, eos_token="</s>", pad_token="<pad>", unk_token="<unk>",
    )


def _llama_like(tmp_path):
    """LLaMA layout: <s> …, BOS only (no EOS appended by the tokenizer)."""
    from tokenizers import processors

    tok = _train_base(["<unk>", "<s>", "</s>"])
    bos = tok.token_to_id("<s>")
    tok.post_processor = processors.TemplateProcessing(
        single="<s> $A", pair="<s> $A $B", special_tokens=[("<s>", bos)],
    )
    return _save_and_load(
        tmp_path, tok,
        bos_token="<s>", eos_token="</s>", unk_token="<unk>", pad_token="</s>",
    )


@pytest.mark.parametrize("family,builder", [("bart", _bart_like), ("t5", _t5_like)])
def test_seq2seq_encode_matches_autotokenizer(tmp_path, family, builder):
    hf, d = builder(tmp_path)
    ours = HFTokenizer(d)
    max_src, max_tgt = 8, 6
    ds = SummarizationDataset(
        RECORDS, ours, max_source_length=max_src, max_target_length=max_tgt
    )
    for i, r in enumerate(RECORDS):
        want_src = hf(r["dialogue"], max_length=max_src, truncation=True)["input_ids"]
        want_tgt = hf(text_target=r["summary"], max_length=max_tgt, truncation=True)["input_ids"]
        assert ds[i].input_ids == want_src
        assert ds[i].labels == want_tgt
        # the family layout really is present (not vacuously equal)
        if family == "bart":
            assert ds[i].input_ids[0] == hf.bos_token_id
        else:
            assert ds[i].input_ids[0] != getattr(hf, "bos_token_id", None)
        assert ds[i].input_ids[-1] == hf.eos_token_id
        assert ds[i].labels[-1] == hf.eos_token_id
        assert len(ds[i].input_ids) <= max_src and len(ds[i].labels) <= max_tgt


def test_causal_encode_matches_autotokenizer(tmp_path):
    hf, d = _llama_like(tmp_path)
    ours = HFTokenizer(d)
    max_len, max_tgt = 16, 6
    ds = CausalLMDataset(RECORDS, ours, max_length=max_len, max_target_length=max_tgt)
    for i, r in enumerate(RECORDS):
        ex = ds[i]
        want_tgt = hf.encode(r["summary"], add_special_tokens=False)[: max_tgt - 1] + [
            hf.eos_token_id
        ]
        want_prompt = hf(r["dialogue"], max_length=max_len - len(want_tgt), truncation=True)[
            "input_ids"
        ]
        # LLaMA layout: BOS opens the document, prompt carries no EOS,
        # continuation has no second BOS and ends the document with EOS
        assert ex.prompt_ids == want_prompt
        assert ex.prompt_ids[0] == hf.bos_token_id
        assert hf.eos_token_id not in ex.prompt_ids
        assert ex.target_ids == want_tgt
        assert ex.target_ids[0] != hf.bos_token_id
        assert ex.input_ids == want_prompt + want_tgt
        assert ex.labels[: len(want_prompt)] == [-100] * len(want_prompt)
        assert ex.labels[len(want_prompt):] == want_tgt


def test_truncation_preserves_trailing_specials(tmp_path):
    """HF truncation keeps the layout's trailing EOS — the property that
    makes `max_length` safe to apply at the tokenizer layer."""
    hf, d = _bart_like(tmp_path)
    ours = HFTokenizer(d)
    long_text = " ".join(["hello world the story"] * 20)
    ids = ours.encode_source(long_text, 7)
    assert len(ids) == 7
    assert ids[0] == hf.bos_token_id and ids[-1] == hf.eos_token_id
