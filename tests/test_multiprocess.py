"""REAL multi-process integration tests (2 procs × 4 devices and the
reference's 4-machine shape, 4 procs × 2 devices).

The reference's distinguishing variant is genuinely multi-machine
(reference train-task.py:404-430: one process per host, NCCL rendezvous
over ``tcp://master:1234``).  Every other test in this suite simulates
multi-host on a single process with 8 virtual devices; these tests spawn
TWO OS processes that rendezvous through ``jax.distributed.initialize``
(gloo collectives over localhost) and run the full Trainer CLI end-to-end,
executing every ``process_count > 1`` branch that is otherwise dead code:

- ``initialize_distributed`` from the VH_* env triple (core/mesh.py)
- ``put_batch``'s ``make_array_from_process_local_data`` (train/step.py)
- the per-epoch bucket-width allgather (data/batching.py)
- cross-host eval row gathering + metric aggregation (evaluation/)
- the cadenced preemption agreement allgather (train/trainer.py)

Loss parity with a single-process 8-device run of the identical config is
the correctness oracle: same global batches, same mesh, same shardings —
the distribution mechanism must be invisible in the math.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = "distributed_llms_example_tpu.launch.cli"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(n_local_devices: int, *, rank: int | None = None,
               world: int | None = None, port: int | None = None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_local_devices}"
    env["PALLAS_AXON_POOL_IPS"] = ""  # keep the axon TPU plugin off
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # never inherit rendezvous facts from an outer context
    for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK", "MASTER_ADDR", "WORLD_SIZE", "RANK"):
        env.pop(k, None)
    if rank is not None:
        env["VH_MASTER_IP"] = f"127.0.0.1:{port}"
        env["VH_WORLD_SIZE"] = str(world)
        env["VH_RANK"] = str(rank)
    return env


def _cli_args(outdir: str, train: str, val: str, **over) -> list[str]:
    opts = {
        "model-ckpt": "t5-test",
        "output-dir": outdir,
        "batch-size": 8,
        "num-epochs": 2,
        "train-file": train,
        "val-file": val,
        "mesh": "data=2,fsdp=2,tensor=2",
        "compute-dtype": "float32",  # exact loss parity across process layouts
        "log-every-steps": 1,
        "num-beams": 1,
        "eval-max-new-tokens": 8,
    }
    opts.update(over)
    args = [sys.executable, "-m", CLI]
    for k, v in opts.items():
        args += [f"--{k}", str(v)]
    return args


def _write_dataset(tmp_path) -> tuple[str, str]:
    recs = [
        {
            "dialogue": f"Speaker A: point {i} about the {i % 7} plan. "
                        f"Speaker B: noted, we will revisit item {i} tomorrow.",
            "summary": f"They discuss point {i} and defer it.",
        }
        for i in range(48)
    ]
    train, val = str(tmp_path / "train.json"), str(tmp_path / "val.json")
    with open(train, "w") as f:
        json.dump(recs[:40], f)
    with open(val, "w") as f:
        json.dump(recs[40:], f)
    return train, val


def _events(stdout: str) -> list[dict]:
    out = []
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def _step_losses(events: list[dict]) -> dict[int, float]:
    return {e["step"]: e["loss"] for e in events if "step" in e and "loss" in e}


@pytest.fixture(scope="module")
def single_reference(tmp_path_factory):
    """One single-process 8-device run shared by every world-size variant:
    the correctness oracle all multi-process layouts must reproduce."""
    base = tmp_path_factory.mktemp("mp_ref")
    train, val = _write_dataset(base)
    single = subprocess.run(
        _cli_args(str(base / "single"), train, val),
        env=_child_env(8), cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert single.returncode == 0, single.stderr[-3000:]
    ev_single = _events(single.stdout)
    losses_single = _step_losses(ev_single)
    assert len(losses_single) == 10  # 40 examples / batch 8 × 2 epochs
    return train, val, ev_single, losses_single


@pytest.mark.slow
@pytest.mark.parametrize("world", [2, 4])
def test_multiprocess_loss_parity(tmp_path, single_reference, world):
    """``world`` procs × 8/world devices must reproduce the single-process
    8-device run bit-for-bit in batches and to float tolerance in
    losses/ROUGE.  world=4 is the reference's flagship 4-machine shape
    (reference valohai.yaml:82-87) and exercises rank>1 metric
    aggregation plus non-trivial by-start host-row ordering in the eval
    gather (evaluation/evaluate.py)."""
    train, val, ev_single, losses_single = single_reference

    port = _free_port()
    # stderr to FILES, not pipes: communicate() drains ranks sequentially,
    # and an undrained 64 KB stderr pipe (gloo/XLA chatter) on a waiting
    # rank would block it mid-write and deadlock a collective — the same
    # hazard the preemption test documents, ×world writers here
    errs = [open(str(tmp_path / f"err{r}.log"), "w") for r in range(world)]
    procs = [
        subprocess.Popen(
            # one SHARED output dir for all ranks: orbax's multi-process
            # save coordinates through the filesystem (every rank commits
            # its shards under the same checkpoint dir); per-rank dirs
            # deadlock its finalize barrier
            _cli_args(str(tmp_path / "multi"), train, val),
            env=_child_env(8 // world, rank=r, world=world, port=port),
            cwd=REPO, stdout=subprocess.PIPE, stderr=errs[r], text=True,
        )
        for r in range(world)
    ]
    outs = []
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        outs.append((p.returncode, out))
    for f in errs:
        f.close()
    assert all(rc == 0 for rc, _ in outs), "\n".join(
        open(str(tmp_path / f"err{r}.log")).read()[-2000:] for r in range(world)
    )

    ev0 = _events(outs[0][1])
    report = next(e for e in ev0 if e.get("event") == "device_report")
    assert report["process_count"] == world and report["global_device_count"] == 8
    losses_multi = _step_losses(ev0)
    assert sorted(losses_multi) == sorted(losses_single)
    for s, loss in losses_single.items():
        assert losses_multi[s] == pytest.approx(loss, rel=2e-4), (
            f"step {s}: single={loss} multi={losses_multi[s]}"
        )
    # eval ran the cross-host row-gather path and agreed on scores
    eval_single = [e for e in ev_single if e.get("event") == "eval"][-1]
    eval_multi = [e for e in ev0 if e.get("event") == "eval"][-1]
    for k in ("rouge1", "rougeL"):
        assert eval_multi[k] == pytest.approx(eval_single[k], abs=1e-6)
    # metrics logging is process-0-only: ranks 1+ must not emit step lines
    for rc, out in outs[1:]:
        assert not _step_losses(_events(out))
    # the final artifact is an HF checkpoint written collaboratively into
    # the shared dir (params gathered across hosts, process 0 writes)
    model_dir = tmp_path / "multi" / "model"
    assert (model_dir / "model.safetensors").is_file()
    assert (model_dir / "config.json").is_file()


@pytest.mark.slow
def test_two_process_preemption_and_resume(tmp_path):
    """SIGTERM on ONE rank must stop BOTH at an agreed step (the cadenced
    allgather), checkpoint, exit cleanly — then a resumed run finishes.

    Bounded retry (4 attempts, fresh dirs/ports each), TARGETED: this
    leg is ENVIRONMENT-flaky — on this container the gloo/coordination
    layer dies in the rendezvous preamble ("op.preamble.length <=
    op.nbytes"), with a mid-run "Connection closed by peer", or with the
    coordination-service heartbeat timeout, at roughly every other
    rendezvous (each cycle runs TWO: initial + resume), verified
    identical at clean pre-change HEAD in a worktree.  Only failures
    matching those infra signatures retry; anything else — a real
    product regression — fails on the FIRST attempt."""
    last: Exception | None = None
    for attempt in range(4):
        root = tmp_path / f"attempt{attempt}"
        root.mkdir()
        # pytest.fail raises Failed, a BaseException subclass Exception
        # does NOT cover — name it explicitly so a deadline fail inside
        # the cycle reaches the signature check instead of skipping it
        try:
            _preemption_and_resume_cycle(root)
            return
        except (Exception, pytest.fail.Exception) as e:
            text = str(e)
            if not any(sig in text for sig in _INFRA_FLAKE_SIGNATURES):
                raise
            last = e
    assert last is not None
    raise last


# the gloo/coordination-service failure modes this container produces on
# an otherwise-green run (see test docstring) — the ONLY failures the
# bounded retry above absorbs
_INFRA_FLAKE_SIGNATURES = (
    "op.preamble",
    "Connection closed by peer",
    "heartbeat timeout",
    "coordination service",
    "CoordinationService",
)


def _preemption_and_resume_cycle(tmp_path):
    train, val = _write_dataset(tmp_path)
    outdir = str(tmp_path / "out")  # shared by both ranks (see above)
    port = _free_port()

    # stderr goes to files: the test reads stdout incrementally, and a
    # PIPE'd stderr nobody drains (gloo/XLA chatter) could fill and block
    # the children
    errs = [open(str(tmp_path / f"err{r}.log"), "w") for r in range(2)]

    def launch(r: int, port_: int, **over) -> subprocess.Popen:
        return subprocess.Popen(
            _cli_args(outdir, train, val, **{"evaluation-steps": 0, **over}),
            env=_child_env(4, rank=r, world=2, port=port_),
            cwd=REPO, stdout=subprocess.PIPE, stderr=errs[r], text=True,
        )

    procs = [launch(r, port, **{"num-epochs": 40}) for r in range(2)]
    # wait until rank 0 has taken a few steps, then SIGTERM rank 0 ONLY
    buf = []
    deadline = time.time() + 420
    while time.time() < deadline:
        line = procs[0].stdout.readline()
        if not line:
            break
        buf.append(line)
        if '"step": 3' in line:
            procs[0].send_signal(signal.SIGTERM)
            break
    else:
        pytest.fail("rank 0 never reached step 3")

    rest0, _ = procs[0].communicate(timeout=420)
    out1, _ = procs[1].communicate(timeout=420)
    for f in errs:
        f.close()
    for r, p in enumerate(procs):
        assert p.returncode == 0, open(str(tmp_path / f"err{r}.log")).read()[-3000:]
    ev0 = _events("".join(buf) + rest0)
    pre = [e for e in ev0 if e.get("event") == "preempted"]
    assert pre, "rank 0 did not checkpoint-and-exit on SIGTERM"
    stopped_at = pre[0]["step"]
    assert stopped_at >= 3
    # the agreed-step checkpoint committed (tmp suffix gone = every rank's
    # shards landed and the finalize barrier passed)
    assert os.path.isdir(os.path.join(outdir, "checkpoints", str(stopped_at)))

    # resume: fresh pair, same output dirs, larger epoch budget than the
    # preempted step so the run both resumes and finishes
    port2 = _free_port()
    errs = [open(str(tmp_path / f"err2_{r}.log"), "w") for r in range(2)]
    procs = [launch(r, port2, **{"num-epochs": 4}) for r in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for f in errs:
        f.close()
    assert all(p.returncode == 0 for p in procs), "\n".join(
        open(str(tmp_path / f"err2_{r}.log")).read()[-2000:] for r in range(2)
    )
    ev = _events(outs[0][0])
    assert any(e.get("event") == "resumed" and e["step"] == stopped_at for e in ev)
    assert any(e.get("event") == "done" for e in ev)
