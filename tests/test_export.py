"""HF-format export round-trips (models/export.py).

Two contracts, per family:

1. framework → export → ``load_model(dir)`` reproduces the exact param
   tree and logits (the converters are mutual inverses);
2. ``transformers.*.from_pretrained(dir)`` loads the artifact with no
   unexpected/mismatched keys and produces the same logits — the artifact
   really is an HF checkpoint, parity with the reference's
   ``model.save_pretrained`` output (reference helpers.py:13).
"""

import dataclasses

import jax
import numpy as np
import pytest

from distributed_llms_example_tpu.models.export import save_hf_checkpoint
from distributed_llms_example_tpu.models.registry import load_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

# (registry name, family key for the exporter, loader kwargs)
FAMILIES = [
    ("t5-test", "t5", {}),
    ("bart-test", "bart", {}),
    ("llama-test", "llama", {}),
    # export/compare in no-drop mode so routing is dense like HF's
    ("mixtral-test", "llama", {"moe_capacity_factor": -1.0}),
]


def _tree_paths(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        p = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_tree_paths(v, p))
        else:
            out[p] = np.asarray(v)
    return out


def _logits(lm, params, ids, mask, dec_ids=None):
    if lm.is_seq2seq:
        return np.asarray(lm.module.apply({"params": params}, ids, mask, dec_ids))
    return np.asarray(lm.module.apply({"params": params}, ids, mask))


@pytest.mark.parametrize("name,family,kw", FAMILIES, ids=[f[0] for f in FAMILIES])
def test_roundtrip_through_our_loader(name, family, kw, tmp_path):
    lm = load_model(name, **kw)
    params = jax.device_get(lm.init_params(0))
    out = str(tmp_path / "export")
    save_hf_checkpoint(out, family, lm.config, params)

    reloaded = load_model(out)
    a, b = _tree_paths(params), _tree_paths(reloaded.params)
    assert set(a) == set(b), (set(a) ^ set(b))
    for p in a:
        np.testing.assert_array_equal(a[p], b[p].astype(a[p].dtype), err_msg=p)

    rng = np.random.RandomState(0)
    ids = rng.randint(3, 250, (2, 12)).astype(np.int32)
    mask = np.ones_like(ids)
    dec = rng.randint(3, 250, (2, 6)).astype(np.int32) if lm.is_seq2seq else None
    np.testing.assert_allclose(
        _logits(lm, params, ids, mask, dec),
        _logits(reloaded, reloaded.params, ids, mask, dec),
        atol=1e-5, rtol=1e-5,
    )


_TIED_OK = ("embed_tokens", "lm_head.weight", "final_logits_bias", "shared.weight")


@pytest.mark.parametrize("name,family,kw", FAMILIES, ids=[f[0] for f in FAMILIES])
def test_transformers_loads_the_export(name, family, kw, tmp_path):
    lm = load_model(name, **kw)
    params = jax.device_get(lm.init_params(0))
    out = str(tmp_path / "export")
    save_hf_checkpoint(out, family, lm.config, params)

    auto = (
        transformers.AutoModelForSeq2SeqLM
        if lm.is_seq2seq
        else transformers.AutoModelForCausalLM
    )
    hf_model, info = auto.from_pretrained(
        out, output_loading_info=True, attn_implementation="eager"
    )
    hf_model = hf_model.eval()
    assert info["unexpected_keys"] == [], info["unexpected_keys"]
    assert info.get("mismatched_keys", []) == []
    # only tie-derived keys may be "missing" (transformers re-ties on load)
    bad = [k for k in info["missing_keys"] if not any(t in k for t in _TIED_OK)]
    assert not bad, bad

    rng = np.random.RandomState(1)
    ids = rng.randint(3, 250, (2, 10)).astype(np.int32)
    mask = np.ones_like(ids)
    with torch.no_grad():
        if lm.is_seq2seq:
            dec = rng.randint(3, 250, (2, 5)).astype(np.int32)
            ref = hf_model(
                input_ids=torch.tensor(ids, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
                decoder_input_ids=torch.tensor(dec, dtype=torch.long),
            ).logits.numpy()
            got = _logits(lm, params, ids, mask, dec)
        else:
            ref = hf_model(
                input_ids=torch.tensor(ids, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
            ).logits.numpy()
            got = _logits(lm, params, ids, mask)
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=3e-3)


def test_large_checkpoint_shards_with_index(tmp_path, monkeypatch):
    """Above the shard budget the writer emits model-0000N-of-0000M files +
    index json — the exact layout _load_local_state_dict reads back."""
    import distributed_llms_example_tpu.models.export as export_mod

    monkeypatch.setattr(export_mod, "MAX_SHARD_BYTES", 64 * 1024)
    lm = load_model("llama-test")
    params = jax.device_get(lm.init_params(0))
    out = str(tmp_path / "export")
    save_hf_checkpoint(out, "llama", lm.config, params)
    import os

    assert os.path.isfile(os.path.join(out, "model.safetensors.index.json"))
    assert not os.path.exists(os.path.join(out, "model.safetensors"))

    reloaded = load_model(out)
    a, b = _tree_paths(params), _tree_paths(reloaded.params)
    assert set(a) == set(b)
    for p in a:
        np.testing.assert_array_equal(a[p], b[p].astype(a[p].dtype), err_msg=p)


def test_trainconfig_capacity_override():
    """--moe-capacity-factor reaches the loaded model config (ADVICE r2)."""
    lm = load_model("mixtral-test", moe_capacity_factor=2.0)
    assert lm.config.moe_capacity_factor == 2.0
    assert dataclasses.asdict(lm.config)["num_experts"] == 4
    # non-MoE families ignore the override
    lm2 = load_model("llama-test", moe_capacity_factor=2.0)
    assert lm2.config.moe_capacity_factor != 2.0 or lm2.config.num_experts == 0
