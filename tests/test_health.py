"""Training-health telemetry (ISSUE 3).

Acceptance pins: the in-graph numerics (param norm, per-bucket update
ratios, non-finite counts) computed INSIDE the compiled step; the
watchdog's detectors on synthetic windows (NaN tripwire, EWMA loss
spike, grad explosion — each attributed to the exact step); the
zero-extra-syncs invariant (device→host conversion pinned to the log
cadence with a counting fake scalar); the flight recorder's bounded ring
and atomic schema-stamped bundle; the injected-NaN end-to-end run
(``--on-anomaly checkpoint`` → rank-attributed ``obs_anomaly`` at the
poisoned step, a resumable checkpoint, a recorder bundle, and an ``obs
report`` that reconstructs all of it); the JSONL schema round-trip for
every event type; the kill-9 durability of the fsync'd sink; and the
repo lint's step-cadence sync rule.

The 2-process report/agreement leg rides the slow tier next to
tests/test_multiprocess.py.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import (
    CheckpointConfig,
    MeshConfig,
    TrainConfig,
)
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.health import (
    Anomaly,
    HealthWatchdog,
    agree_and_emit,
    health_enabled,
    to_host,
)
from distributed_llms_example_tpu.obs.recorder import FlightRecorder, batch_fingerprint
from distributed_llms_example_tpu.obs.report import (
    build_report,
    load_jsonl,
    merge_timeline,
    render_markdown,
    straggler_attribution,
)
from distributed_llms_example_tpu.train.step import (
    HEALTH_BUCKETS,
    HEALTH_METRIC_KEYS,
    bucket_of_path,
)


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


# ---------------------------------------------------------------------------
# in-graph numerics: buckets + the health-enabled compiled step
# ---------------------------------------------------------------------------

class _Key:
    def __init__(self, key):
        self.key = key


def test_bucket_of_path_covers_model_families():
    assert bucket_of_path((_Key("embed_tokens"), _Key("embedding"))) == "embed"
    assert bucket_of_path((_Key("shared"), _Key("embedding"))) == "embed"
    assert bucket_of_path((_Key("block_0"), _Key("self_attn"), _Key("q_proj"))) == "attn"
    assert bucket_of_path((_Key("encoder"), _Key("cross_attn"), _Key("o_proj"))) == "attn"
    assert bucket_of_path((_Key("block_1"), _Key("mlp"), _Key("wi"))) == "mlp"
    assert bucket_of_path((_Key("lm_head"), _Key("kernel"))) == "head"
    # norms/bias fall to mlp — the bucket map must be total
    assert bucket_of_path((_Key("final_norm"), _Key("scale"))) == "mlp"
    # stacked pipeline trees keep leaf names under stacked_blocks
    assert bucket_of_path((_Key("stacked_blocks"), _Key("self_attn"), _Key("k_proj"))) == "attn"


@pytest.mark.slow  # ~11s health-step compile: slow tier (the injected
# -NaN trainer e2e keeps in-graph numerics covered fast)
def test_health_metrics_ride_the_compiled_step(dp_mesh, tiny_llama4):
    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.train.optim import make_optimizer
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params = tiny_llama4
    tx, schedule = make_optimizer(learning_rate=1e-3, warmup_steps=0, total_steps=100)
    state = create_train_state(params, tx)
    sh = state_shardings(state, dp_mesh)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    build = make_train_step(
        module, cfg, tx, schedule, dp_mesh, is_seq2seq=False, health=True, donate=False
    )
    step_fn, _ = build(state)
    rng = np.random.RandomState(0)
    ids = rng.randint(2, cfg.vocab_size - 4, (8, 16)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    gb = put_batch(
        {"input_ids": ids, "attention_mask": np.ones_like(ids), "labels": labels},
        dp_mesh,
    )
    _, m = step_fn(state, gb)
    assert set(HEALTH_METRIC_KEYS) <= set(m)
    assert float(m["param_norm"]) > 0
    assert float(m["nonfinite_count"]) == 0.0
    for b in HEALTH_BUCKETS:
        r = float(m[f"update_ratio_{b}"])
        assert np.isfinite(r) and 0 < r < 1.0  # healthy AdamW step sizes
    # inject one NaN parameter element: the tripwire numerics must see it
    flat, treedef = jax.tree.flatten(state.params)
    flat[0] = flat[0].at[(0,) * flat[0].ndim].set(jnp.nan)
    _, m2 = step_fn(state.replace(params=jax.tree.unflatten(treedef, flat)), gb)
    assert not np.isfinite(float(m2["loss"]))
    assert float(m2["nonfinite_count"]) > 0
    # health=False keeps the old metrics contract exactly
    build0 = make_train_step(
        module, cfg, tx, schedule, dp_mesh, is_seq2seq=False, donate=False
    )
    step0, _ = build0(state)
    _, m0 = step0(state, gb)
    assert set(m0) == {"loss", "learning_rate", "grad_norm", "target_tokens"}


# ---------------------------------------------------------------------------
# watchdog: detectors on synthetic windows, step attribution
# ---------------------------------------------------------------------------

def _entries(losses, grads=None, nonfinite=None, start=1):
    out = []
    for i, loss in enumerate(losses):
        out.append((
            start + i,
            {
                "loss": loss,
                "grad_norm": grads[i] if grads else 1.0,
                "nonfinite_count": nonfinite[i] if nonfinite else 0.0,
            },
        ))
    return out


def test_watchdog_nonfinite_tripwire_attributes_the_step():
    wd = HealthWatchdog(warmup_steps=1000)  # detectors unarmed: tripwire only
    anomalies = wd.check(_entries([2.0, 2.0, float("nan"), 2.0], start=7))
    assert len(anomalies) == 1
    assert anomalies[0].code == "nonfinite" and anomalies[0].step == 9
    # nonfinite grad elements trip even with a finite loss
    wd = HealthWatchdog(warmup_steps=1000)
    anomalies = wd.check(_entries([2.0, 2.0], nonfinite=[0.0, 12.0], start=1))
    assert anomalies[0].code == "nonfinite" and anomalies[0].step == 2
    assert anomalies[0].value == 12.0


def test_watchdog_loss_spike_ewma():
    wd = HealthWatchdog(loss_spike_factor=4.0, warmup_steps=10)
    noise = [2.0 + 0.05 * ((-1) ** i) for i in range(30)]
    assert wd.check(_entries(noise, start=1)) == []
    # a 4x-deviation spike at step 31 fires exactly there
    anomalies = wd.check(_entries([8.0], start=31))
    assert len(anomalies) == 1
    assert anomalies[0].code == "loss_spike" and anomalies[0].step == 31
    # a smoothly DECREASING loss never trips (the no-false-positive case)
    wd = HealthWatchdog(loss_spike_factor=4.0, warmup_steps=10)
    dec = [5.0 * (0.99 ** i) for i in range(100)]
    assert wd.check(_entries(dec, start=1)) == []


def test_watchdog_grad_explosion():
    wd = HealthWatchdog(grad_norm_factor=10.0, warmup_steps=5)
    grads = [1.0] * 10 + [50.0]
    anomalies = wd.check(_entries([2.0] * 11, grads=grads, start=1))
    assert len(anomalies) == 1
    assert anomalies[0].code == "grad_explosion" and anomalies[0].step == 11
    # absolute cap works before warmup
    wd = HealthWatchdog(grad_norm_max=5.0, warmup_steps=1000)
    anomalies = wd.check(_entries([2.0], grads=[7.0], start=3))
    assert anomalies[0].code == "grad_explosion" and anomalies[0].step == 3
    # flagged FINITE samples still re-baseline the EWMAs: a permanent
    # level shift fires, then stops firing once the baseline catches up
    # (no anomaly-spam-forever on a healthy new plateau)
    wd = HealthWatchdog(grad_norm_factor=10.0, warmup_steps=5, ewma_alpha=0.2)
    wd.check(_entries([2.0] * 10, grads=[1.0] * 10, start=1))
    assert wd.check(_entries([2.0], grads=[100.0], start=11)) != []  # fires at the shift
    assert wd.grad_ewma > 1.0  # the shift is being absorbed
    fired = [
        bool(wd.check(_entries([2.0], grads=[100.0], start=12 + i)))
        for i in range(10)
    ]
    assert not fired[-1]  # the new plateau re-baselines; firing stops


def test_agree_and_emit_single_process(capsys):
    rec = agree_and_emit(
        [Anomaly(step=9, code="nonfinite", value=float("nan"), detail="loss=nan")],
        step=10,
        policy="checkpoint",
    )
    assert rec is not None
    assert rec["step"] == 9 and rec["detected_at_step"] == 10
    assert rec["code"] == "nonfinite" and rec["ranks"] == [0]
    assert rec["policy"] == "checkpoint" and rec["value"] == "nan"
    lines = _json_lines(capsys.readouterr().out)
    assert any(r.get("event") == "obs_anomaly" and r["step"] == 9 for r in lines)
    # no anomalies anywhere → no event, no record
    assert agree_and_emit([], step=10, policy="warn") is None


def test_health_enabled_tristate():
    assert health_enabled(TrainConfig(health="on", obs="stdout"))
    assert not health_enabled(TrainConfig(health="off", obs="jsonl"))
    assert health_enabled(TrainConfig(health="auto", obs="jsonl"))
    assert not health_enabled(TrainConfig(health="auto", obs="stdout"))


# ---------------------------------------------------------------------------
# organic host-loss detection, first slice (ISSUE 15 satellite):
# persistent heartbeat laggards → host_loss_suspect
# ---------------------------------------------------------------------------


def test_laggard_streaks_classification():
    from distributed_llms_example_tpu.obs.health import LaggardStreaks

    st = LaggardStreaks(suspect_beats=3)
    assert st.update([1], step=10) == []
    assert st.update([1, 2], step=20) == []
    out = st.update([1], step=30)  # rank 1 hits 3 consecutive; rank 2 reset
    assert [s["rank"] for s in out] == [1]
    assert out[0]["event"] == "host_loss_suspect"
    assert out[0]["consecutive_beats"] == 3 and out[0]["step"] == 30
    # already suspected: no re-fire while the streak continues
    assert st.update([1], step=40) == []
    # recovery re-arms; a NEW persistent lag fires again
    assert st.update([], step=50) == []
    for step in (60, 70):
        assert st.update([1], step=step) == []
    assert [s["rank"] for s in st.update([1], step=80)] == [1]


def test_heartbeat_emits_host_loss_suspect(monkeypatch, capsys):
    """The wired path: a rank persistently late at the heartbeat gather
    becomes one pod-agreed host_loss_suspect event (detection + report
    row only — no policy action), computed from the SAME gathered probe
    on every rank."""
    from distributed_llms_example_tpu.obs import heartbeat as hb_mod
    from distributed_llms_example_tpu.obs.heartbeat import Heartbeat

    base = 1_700_000_000
    clock = {"t": 0}

    def fake_gather(local):
        # rank 0 = this process's probe; rank 1 arrives 9 s late (over
        # the 5 s laggard threshold) at every beat
        t = base + clock["t"]
        return np.asarray(
            [[local[0], t, 0], [local[0], t + 9, 0]], np.int32
        )

    monkeypatch.setattr(hb_mod, "gather_probe", fake_gather)
    hb = Heartbeat(every_steps=2, suspect_beats=2)
    recs = []
    for step in (2, 4, 6):
        clock["t"] += 60
        recs.append(hb.beat(step))
    assert all(r is not None and r["laggards"] == [1] for r in recs)
    events = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    suspects = [e for e in events if e.get("event") == "host_loss_suspect"]
    assert len(suspects) == 1  # fires once at the threshold, not per beat
    assert suspects[0]["rank"] == 1
    assert suspects[0]["consecutive_beats"] == 2 and suspects[0]["step"] == 4


def test_heartbeat_suspect_beats_zero_disables(monkeypatch, capsys):
    """Review fix: 0 = classification off (the heartbeat knob
    convention) — no host_loss_suspect ever fires, instead of 0
    silently meaning the default."""
    from distributed_llms_example_tpu.obs import heartbeat as hb_mod
    from distributed_llms_example_tpu.obs.heartbeat import Heartbeat

    monkeypatch.setattr(
        hb_mod, "gather_probe",
        lambda local: np.asarray(
            [[local[0], 1_700_000_000, 0],
             [local[0], 1_700_000_009, 0]], np.int32
        ),
    )
    hb = Heartbeat(every_steps=2, suspect_beats=0)
    assert hb.streaks is None
    for step in (2, 4, 6, 8):
        hb.beat(step)
    events = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    assert not [e for e in events if e.get("event") == "host_loss_suspect"]
    assert [e for e in events if e.get("event") == "heartbeat"]


def test_report_renders_host_loss_suspects(tmp_path):
    from distributed_llms_example_tpu.obs.report import (
        build_report,
        render_markdown,
    )
    from distributed_llms_example_tpu.obs.sink import SCHEMA_VERSION

    obs = tmp_path / "obs"
    obs.mkdir()
    lines = [
        {"schema_version": SCHEMA_VERSION, "event": "host_loss_suspect",
         "rank": 1, "step": 40, "consecutive_beats": 3},
        # a second rank's copy of the SAME verdict dedups to one row
        {"schema_version": SCHEMA_VERSION, "event": "host_loss_suspect",
         "rank": 1, "step": 40, "consecutive_beats": 3},
    ]
    (obs / "metrics-p000.jsonl").write_text(
        "\n".join(json.dumps(r) for r in lines[:1]) + "\n"
    )
    (obs / "metrics-p001.jsonl").write_text(
        "\n".join(json.dumps(r) for r in lines[1:]) + "\n"
    )
    report = build_report(str(tmp_path))
    sus = report["recovery"]["host_loss_suspects"]
    assert sus == [{"rank": 1, "step": 40, "consecutive_beats": 3}]
    # detection only: NOT a fault, so --strict stays green on it
    assert report["recovery"]["organic_faults"] == []
    md = render_markdown(report)
    assert "host_loss_suspect" in md and "rank 1" in md


# ---------------------------------------------------------------------------
# the zero-extra-syncs invariant: conversions pinned to the log cadence
# ---------------------------------------------------------------------------

class CountingScalar:
    """Stands in for a 0-d device array: every host conversion counts."""

    conversions = 0

    def __init__(self, value: float):
        self.value = value

    def __float__(self) -> float:
        CountingScalar.conversions += 1
        return self.value


def test_conversions_only_on_the_log_cadence(tmp_path):
    from distributed_llms_example_tpu.obs import TrainerObs

    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", health="on",
        log_every_steps=4, recorder_steps=16,
    )
    obs = TrainerObs(cfg, start_step=0)
    assert obs.watchdog is not None and obs.recorder is not None
    CountingScalar.conversions = 0
    for step in (1, 2, 3):
        with obs.step_span():
            pass
        action = obs.on_step(
            step, 0,
            {"loss": CountingScalar(2.0), "grad_norm": CountingScalar(1.0),
             "nonfinite_count": CountingScalar(0.0)},
        )
        assert action == "ok"
        # OFF-cadence steps: recorder append + pending append, ZERO
        # device→host conversions (the async-dispatch invariant)
        assert CountingScalar.conversions == 0
    with obs.step_span():
        pass
    obs.on_step(
        4, 0,
        {"loss": CountingScalar(2.0), "grad_norm": CountingScalar(1.0),
         "nonfinite_count": CountingScalar(0.0)},
    )
    # the cadence step converts the whole window (4 steps × 3 scalars)
    assert CountingScalar.conversions == 12
    sink_mod.current_sink().close()


# ---------------------------------------------------------------------------
# flight recorder: bounded ring, annotate, atomic schema-stamped dump
# ---------------------------------------------------------------------------

def test_recorder_ring_and_atomic_dump(tmp_path, capsys):
    rec = FlightRecorder(capacity=4)
    for step in range(1, 11):
        rec.record(step, 0, {"loss": float(step)}, {"epoch": 0, "epoch_step": step})
    assert len(rec) == 4
    rec.annotate(10, {"loss": 10.0, "grad_norm": 3.0})
    path = rec.dump(
        str(tmp_path), reason="anomaly:nonfinite", step=10,
        anomalies=[Anomaly(step=9, code="nonfinite", value=1.0, detail="d")],
    )
    assert path is not None and os.path.exists(path)
    assert not os.path.exists(path + ".tmp")  # atomic: no torn temp left
    bundle = json.load(open(path))
    assert bundle["schema_version"] == 1
    assert bundle["reason"] == "anomaly:nonfinite" and bundle["step"] == 10
    assert [e["step"] for e in bundle["entries"]] == [7, 8, 9, 10]
    assert bundle["entries"][-1]["metrics"]["grad_norm"] == 3.0
    assert bundle["anomalies"][0]["code"] == "nonfinite"
    # non-finite metric values serialize as strings, not bare NaN literals
    rec.record(11, 0, {"loss": float("nan")})
    p2 = rec.dump(str(tmp_path), reason="exception", step=11)
    assert json.load(open(p2))["entries"][-1]["metrics"]["loss"] == "nan"
    lines = _json_lines(capsys.readouterr().out)
    assert any(r.get("event") == "recorder_dump" for r in lines)


def test_batch_fingerprint_identity():
    b = {
        "input_ids": np.arange(12, dtype=np.int32).reshape(3, 4),
        "attention_mask": np.ones((3, 4), np.int32),
        "labels": np.arange(6, dtype=np.int32).reshape(3, 2),
    }
    fp = batch_fingerprint(b, epoch=1, epoch_step=5)
    assert fp["shapes"]["input_ids"] == [3, 4]
    assert fp["epoch"] == 1 and fp["epoch_step"] == 5
    # deterministic, content-sensitive
    assert fp == batch_fingerprint(b, epoch=1, epoch_step=5)
    b2 = {k: v.copy() for k, v in b.items()}
    b2["input_ids"][0, 0] += 1
    assert batch_fingerprint(b2, epoch=1, epoch_step=5)["input_ids_crc32"] != fp["input_ids_crc32"]


# ---------------------------------------------------------------------------
# TrainerObs policy actions (hand-driven; the real loop is the e2e below)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("policy,expected", [
    ("warn", "warn"), ("halt", "halt"), ("checkpoint", "checkpoint"),
])
def test_anomaly_policy_actions(tmp_path, policy, expected):
    from distributed_llms_example_tpu.obs import TrainerObs

    cfg = TrainConfig(
        output_dir=str(tmp_path / policy), obs="jsonl", health="on",
        on_anomaly=policy, log_every_steps=2, recorder_steps=8,
    )
    obs = TrainerObs(cfg, start_step=0)
    with obs.step_span():
        pass
    assert obs.on_step(1, 0, {"loss": 2.0, "grad_norm": 1.0, "nonfinite_count": 0.0}) == "ok"
    with obs.step_span():
        pass
    action = obs.on_step(
        2, 0, {"loss": float("nan"), "grad_norm": 1.0, "nonfinite_count": 5.0}
    )
    assert action == expected
    # any anomaly (whatever the policy) dumps the flight recorder
    bundle_path = obs.recorder.bundle_path(cfg.output_dir)
    assert os.path.exists(bundle_path)
    bundle = json.load(open(bundle_path))
    assert bundle["reason"] == "anomaly:nonfinite"
    assert bundle["anomalies"][0]["step"] == 2
    sink_mod.current_sink().close()


# ---------------------------------------------------------------------------
# the injected-NaN end-to-end acceptance run
# ---------------------------------------------------------------------------

def test_trainer_injected_nan_checkpoint_and_report(tmp_path):
    """The acceptance criterion end to end: a NaN injected at step 3 of a
    real --obs jsonl run trips ``obs_anomaly`` with the correct step and
    rank, ``--on-anomaly checkpoint`` stops the run with a resumable
    checkpoint + flight-recorder bundle, and ``obs report`` over the
    output dir reconstructs the timeline with the anomaly on it."""
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(0)
    recs = [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(12)),
            "summary": f"w{rng.randint(40)}",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=3,
        warmup_steps=1,
        evaluation_steps=0,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=2,
        num_beams=1,
        tokenizer="byte",
        mesh=MeshConfig(data=-1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        obs="jsonl",
        obs_gauges="off",  # gauge compile not under test here
        health="on",
        on_anomaly="checkpoint",
        recorder_steps=8,
    )
    trainer = Trainer(cfg, train_records=recs)
    trainer.save_final = lambda: None
    trainer._poison_nan_at_step = 3  # the injected-NaN test hook
    result = trainer.train()

    # the run stopped at the detecting cadence step with the policy action
    assert result.get("anomaly") == "checkpoint"
    assert result["steps"] == 4  # cadence 2: NaN at 3 detected at 4

    # obs_anomaly carries the poisoned step and the detecting rank
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records = [json.loads(line) for line in open(path)]
    anomaly = next(r for r in records if r.get("event") == "obs_anomaly")
    assert anomaly["step"] == 3 and anomaly["detected_at_step"] == 4
    assert anomaly["code"] == "nonfinite" and anomaly["ranks"] == [0]
    assert anomaly["policy"] == "checkpoint"

    # a RESUMABLE checkpoint was force-saved at the stop step
    assert trainer.checkpointer.latest_step() == 4

    # the flight-recorder bundle holds the poisoned step's evidence
    bundle_path = os.path.join(str(tmp_path), "obs", "flight-recorder-p000.json")
    bundle = json.load(open(bundle_path))
    assert bundle["reason"] == "anomaly:nonfinite"
    by_step = {e["step"]: e for e in bundle["entries"]}
    assert by_step[3]["metrics"]["loss"] == "nan"
    assert float(by_step[3]["metrics"]["nonfinite_count"]) > 0
    assert float(by_step[2]["metrics"]["nonfinite_count"]) == 0
    assert by_step[3]["fingerprint"]["shapes"]["input_ids"][0] == 8
    assert "input_ids_crc32" in by_step[3]["fingerprint"]

    # obs report reconstructs the run: anomaly on the timeline, recorder
    # named, schema clean
    report = build_report(str(tmp_path))
    assert report["schema_errors"] == []
    assert report["anomalies"][0]["step"] == 3
    row3 = next(r for r in report["timeline"] if r["step"] == 3)
    assert row3["anomalies"][0]["code"] == "nonfinite"
    assert report["recorders"]["0"]["reason"] == "anomaly:nonfinite"
    md = render_markdown(report)
    assert "nonfinite" in md and "flight recorder p0" in md


# ---------------------------------------------------------------------------
# satellite: eval events carry the global step like train events
# ---------------------------------------------------------------------------

def test_eval_event_carries_step_field(capsys):
    from distributed_llms_example_tpu.train.trainer import Trainer

    t = object.__new__(Trainer)  # evaluate() only touches these attrs
    t.val_ds = [1]
    t.pipelined = False
    t.evaluator = None
    t._pipeline_rouge_ok = False
    t.cfg = TrainConfig()
    scores = Trainer.evaluate(t, epoch=2, step=37)
    lines = _json_lines(capsys.readouterr().out)
    ev = next(r for r in lines if r.get("event") == "eval")
    assert ev["step"] == 37 and ev["epoch"] == 2.0
    assert scores["epoch"] == 2.0


# ---------------------------------------------------------------------------
# satellite: JSONL schema round-trip for every event type
# ---------------------------------------------------------------------------

def test_schema_round_trip_every_event_type(tmp_path, capsys):
    """Every event type the telemetry stack emits parses back through
    obs/report.py's loader with schema_version checked — spans windows,
    gauges, heartbeat, health, recorder, profiler, plus the plain metric
    lines."""
    from distributed_llms_example_tpu.obs import TrainerObs
    from distributed_llms_example_tpu.obs.gauges import collective_traffic
    from distributed_llms_example_tpu.obs.heartbeat import Heartbeat
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", health="on",
        log_every_steps=1, recorder_steps=4, obs_heartbeat_steps=1,
    )
    obs = TrainerObs(cfg, start_step=0)
    obs.flops_per_step = 1e9
    # spans window + heartbeat + (clean) health on step 1
    with obs.step_span():
        pass
    obs.on_step(1, 0, {"loss": 1.0, "grad_norm": 1.0, "nonfinite_count": 0.0})
    # health anomaly (+ recorder dump) on step 2
    with obs.step_span():
        pass
    obs.on_step(2, 0, {"loss": float("nan"), "grad_norm": 1.0, "nonfinite_count": 1.0})
    # gauges record (the account computed from a hand HLO — no compile)
    acct = collective_traffic(
        "  %ar = f32[8]{0} all-reduce(f32[8]{0} %x), replica_groups={{0,1}}, to_apply=%add\n",
        [8], 2,
    )
    sink_mod.emit({"event": "obs_gauges", "flops_per_step": 1e9,
                   "flops_source": "test", "mesh": {"data": 2}, "comm": acct})
    Heartbeat(every_steps=1).beat(2)
    # profiler event shape (emitted without a real trace)
    sink_mod.emit({"event": "profile_trace", "dir": str(tmp_path)}, all_processes=True)
    # the plain metric line + eval line
    log_json({"step": 2, "loss": 1.0, "learning_rate": 1e-4})
    log_json({"event": "eval", "step": 2, "val_loss": 1.5})
    sink_mod.current_sink().close()

    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records, errors = load_jsonl(path)
    assert errors == []
    events = {r.get("event", "metric") for r in records}
    assert {
        "obs_window", "obs_anomaly", "recorder_dump", "obs_gauges",
        "heartbeat", "profile_trace", "eval", "metric",
    } <= events
    assert all(r["schema_version"] == 1 for r in records)
    # and the report consumes the lot without complaint
    report = build_report(str(tmp_path))
    assert report["schema_errors"] == []
    assert report["comm"] is not None
    render_markdown(report)

    # the loader REJECTS schema drift and torn lines, per line
    bad = tmp_path / "obs" / "metrics-p001.jsonl"
    with open(bad, "w") as f:
        f.write(json.dumps({"schema_version": 99, "event": "x"}) + "\n")
        f.write(json.dumps({"event": "no_stamp"}) + "\n")
        f.write('{"torn": ')  # kill mid-write
    recs, errs = load_jsonl(str(bad))
    assert recs == [] and len(errs) == 3


# ---------------------------------------------------------------------------
# report: merged timeline + straggler attribution from hand-built streams
# ---------------------------------------------------------------------------

def _stamp(rec: dict) -> dict:
    return {"schema_version": 1, **rec}


def test_report_merges_cross_host_timeline(tmp_path):
    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir)
    p0 = [
        _stamp({"step": 2, "loss": 2.5, "learning_rate": 1e-4, "tokens_per_sec": 100.0}),
        _stamp({"event": "obs_window", "step": 2, "step_ms_p50": 10.0,
                "step_ms_p95": 12.0, "step_ms_max": 12.0, "straggler": False}),
        _stamp({"event": "heartbeat", "step": 2, "process_count": 2,
                "skew_steps": 0, "arrival_spread_s": 6.0, "laggards": [1]}),
        _stamp({"event": "eval", "step": 2, "val_loss": 2.1}),
        _stamp({"event": "obs_anomaly", "step": 3, "detected_at_step": 4,
                "code": "loss_spike", "ranks": [1], "policy": "warn"}),
        _stamp({"step": 4, "loss": 9.0}),
    ]
    p1 = [
        _stamp({"event": "obs_window", "step": 2, "step_ms_p50": 16.0,
                "step_ms_p95": 19.0, "step_ms_max": 25.0, "straggler": True}),
    ]
    for idx, recs in ((0, p0), (1, p1)):
        with open(obs_dir / f"metrics-p{idx:03d}.jsonl", "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
    processes = {0: p0, 1: p1}
    timeline = merge_timeline(processes)
    row2 = next(r for r in timeline if r["step"] == 2)
    assert row2["loss"] == 2.5 and row2["eval"]["val_loss"] == 2.1
    # BOTH ranks' windows land on the same step row
    assert row2["windows"][0]["p50"] == 10.0
    assert row2["windows"][1]["p50"] == 16.0 and row2["windows"][1]["straggler"]
    assert row2["heartbeat"]["laggards"] == [1]
    row3 = next(r for r in timeline if r["step"] == 3)
    assert row3["anomalies"][0]["ranks"] == [1]
    # straggler attribution: rank 1 named by the heartbeat AND slowest p95
    s = straggler_attribution(processes)
    assert s["heartbeat_laggard_counts"] == {"1": 1}
    assert s["max_arrival_spread_s"] == 6.0
    assert s["mean_step_ms_p95_by_rank"] == {"0": 12.0, "1": 19.0}
    assert s["straggler_windows_by_rank"] == {"0": 0, "1": 1}
    # the full report + markdown over the same dir
    report = build_report(str(tmp_path))
    assert report["processes"] == [0, 1]
    md = render_markdown(report)
    assert "rank 1: named laggard in 1 heartbeat(s)" in md
    assert "loss_spike@ranks[1]" in md


def test_report_cli_main(tmp_path, capsys):
    from distributed_llms_example_tpu.obs import report as report_mod

    obs_dir = tmp_path / "obs"
    os.makedirs(obs_dir)
    with open(obs_dir / "metrics-p000.jsonl", "w") as f:
        f.write(json.dumps(_stamp({"step": 1, "loss": 1.0})) + "\n")
        f.write(json.dumps({"event": "drifted"}) + "\n")  # no stamp
    assert report_mod.main([str(tmp_path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["records"] == 1 and len(out["schema_errors"]) == 1
    # --strict turns schema drift into a nonzero exit
    assert report_mod.main([str(tmp_path), "--strict"]) == 1
    capsys.readouterr()
    # no obs dir at all → usage error
    assert report_mod.main([str(tmp_path / "nowhere")]) == 2


# ---------------------------------------------------------------------------
# satellite: the fsync'd sink + recorder bundle survive a kill -9
# ---------------------------------------------------------------------------

def test_sink_and_recorder_survive_kill9(tmp_path):
    """A subprocess writes JSONL telemetry + a recorder bundle, flushes
    with fsync (the anomaly-path durability contract), then SIGKILLs
    itself mid-run.  Everything flushed before the kill must parse."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = """
import json, os, signal
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.recorder import FlightRecorder

out = os.environ["K9_OUT"]
sink_mod.install_sink(sink_mod.build_sink("jsonl", out))
for step in range(1, 6):
    sink_mod.emit({"event": "obs_window", "step": step, "step_ms_p50": 1.0}, local=True)
rec = FlightRecorder(capacity=4)
for step in range(1, 6):
    rec.record(step, 0, {"loss": float(step)})
rec.dump(out, reason="anomaly:test", step=5)   # atomic + fsync'd
sink_mod.flush(fsync=True)                     # the anomaly-path flush
os.kill(os.getpid(), signal.SIGKILL)           # kill -9, no cleanup runs
"""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        "K9_OUT": str(tmp_path),
    })
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=repo,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == -9, proc.stderr[-2000:]  # really SIGKILLed
    records, errors = load_jsonl(str(tmp_path / "obs" / "metrics-p000.jsonl"))
    assert errors == []
    windows = [r for r in records if r.get("event") == "obs_window"]
    assert [r["step"] for r in windows] == [1, 2, 3, 4, 5]
    assert any(r.get("event") == "recorder_dump" for r in records)
    bundle = json.load(open(tmp_path / "obs" / "flight-recorder-p000.json"))
    assert bundle["reason"] == "anomaly:test"
    assert [e["step"] for e in bundle["entries"]] == [2, 3, 4, 5]
    assert not os.path.exists(str(tmp_path / "obs" / "flight-recorder-p000.json.tmp"))


# ---------------------------------------------------------------------------
# CI/tooling: the repo lint's step-cadence sync rule
# ---------------------------------------------------------------------------

def _load_repo_lint():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "repo_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "repo_lint.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_lint_step_cadence_sync_rule(tmp_path):
    repo_lint = _load_repo_lint()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "class R:\n"
        "    def record(self, m):\n"
        "        self.v = float(m['loss'])\n"        # per-step conversion
        "    def step_hook(self, m):\n"
        "        x = m['loss'].item()\n"             # per-step .item()
        "        y = jax.device_get(m['loss'])\n"    # per-step device_get
        "        return x, y\n"
        "    def dump(self, m):\n"
        "        return float(m['loss'])\n"          # allowed window func
    )
    rel = os.path.join("distributed_llms_example_tpu", "obs", "recorder.py")
    violations = repo_lint.lint_file(str(bad), rel)
    assert len(violations) == 3
    assert all("step-cadence" in v for v in violations)
    # same code outside a step-cadence file: no rule-4 findings
    rel = os.path.join("distributed_llms_example_tpu", "obs", "gauges.py")
    assert repo_lint.lint_file(str(bad), rel) == []
    # and the repo itself is clean under the new rule
    assert repo_lint.main([]) == 0


# ---------------------------------------------------------------------------
# 2-process leg: per-process JSONL streams, rank-attributed agreement, and
# the merged report (the acceptance's cross-host reconstruction)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_process_report_and_agreement(tmp_path):
    """Two real OS processes share one output dir: each writes its OWN
    obs_window stream (rank 1 runs slow steps), the heartbeat names rank
    1 a laggard, and a rank-1-only anomaly is agreed — then ``obs
    report`` over the shared dir reconstructs the merged per-step
    timeline with straggler attribution."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = """
import json, os, sys, time
import jax
from distributed_llms_example_tpu.core.mesh import initialize_distributed
initialize_distributed(os.environ["HR_COORD"], 2, int(os.environ["HR_RANK"]))
from distributed_llms_example_tpu.core.config import TrainConfig
from distributed_llms_example_tpu.obs import TrainerObs
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.health import Anomaly, agree_and_emit

rank = jax.process_index()
cfg = TrainConfig(
    output_dir=os.environ["HR_OUT"], obs="jsonl", health="off",
    log_every_steps=2, obs_heartbeat_steps=2, recorder_steps=0,
)
obs = TrainerObs(cfg, start_step=0, manage_sink=True)
obs.heartbeat.laggard_threshold_s = 1.0  # the 1.5 s sleep must register
for step in (1, 2, 3, 4):
    with obs.step_span():
        time.sleep(0.01 if rank == 0 else 0.05)  # rank 1 is slow
    if rank == 1 and step == 2:
        time.sleep(1.5)  # heartbeat laggard at the step-2 beat
    obs.on_step(step, 0, {})
# rank-1-only anomaly, agreed over the heartbeat channel at step 4
local = [] if rank == 0 else [Anomaly(step=3, code="loss_spike", value=9.0, detail="test")]
rec = agree_and_emit(local, step=4, policy="warn")
assert rec is not None and rec["ranks"] == [1], rec  # BOTH ranks agree
assert rec["step"] == 3 and rec["code"] == "loss_spike"
sink_mod.current_sink().close()
print("AGREED " + json.dumps(rec))
"""
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
            "HR_COORD": f"127.0.0.1:{port}",
            "HR_RANK": str(rank),
            "HR_OUT": str(tmp_path),
        })
        for k in ("VH_MASTER_IP", "VH_WORLD_SIZE", "VH_RANK"):
            env.pop(k, None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = [p.communicate(timeout=300) for p in procs]
    assert all(p.returncode == 0 for p in procs), outs[0][1][-2000:] + outs[1][1][-2000:]
    # both ranks saw the same agreed record
    assert any(ln.startswith("AGREED ") for ln in outs[0][0].splitlines())

    # every process wrote its own stream; the report merges them
    for idx in (0, 1):
        recs, errs = load_jsonl(str(tmp_path / "obs" / f"metrics-p{idx:03d}.jsonl"))
        assert errs == []
        assert any(r.get("event") == "obs_window" for r in recs)
    report = build_report(str(tmp_path))
    assert report["processes"] == [0, 1]
    assert report["schema_errors"] == []
    # merged timeline: both ranks' windows on the cadence steps
    row = next(r for r in report["timeline"] if r["step"] == 2)
    assert set(row["windows"]) == {0, 1}
    # rank 1's steps are measurably slower on its own stream
    assert row["windows"][1]["p50"] > row["windows"][0]["p50"]
    # straggler attribution: the heartbeat (p0's stream) named rank 1.
    # NOTE self-timed p95s CANNOT distinguish the ranks here — the
    # heartbeat gather is a barrier, so rank 0's wait for sleeping rank 1
    # lands in rank 0's own next step duration; that equalization is
    # exactly why attribution comes from the heartbeat's arrival spread
    s = report["stragglers"]
    assert s["heartbeat_laggard_counts"].get("1", 0) >= 1
    assert s["max_arrival_spread_s"] >= 1.0
    assert set(s["mean_step_ms_p95_by_rank"]) == {"0", "1"}
    # the agreed anomaly (emitted by p0) rides the merged timeline
    row3 = next(r for r in report["timeline"] if r["step"] == 3)
    assert row3["anomalies"][0]["code"] == "loss_spike"
    assert row3["anomalies"][0]["ranks"] == [1]
    md = render_markdown(report)
    assert "named laggard" in md
