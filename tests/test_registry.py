"""Model registry: name resolution, local checkpoint loading, sharded init."""

import json
import os

import numpy as np
import pytest

from distributed_llms_example_tpu.models.registry import T5_CONFIGS, load_model


def test_builtin_names():
    lm = load_model("t5-test")
    assert lm.family == "t5" and lm.config.d_model == 64
    params = lm.init_params(0)
    assert params["shared"]["embedding"].shape == (256, 64)
    # org prefixes are stripped
    lm2 = load_model("google/flan-t5-xl", load_weights=False)
    assert lm2.config.is_gated and not lm2.config.tie_word_embeddings


def test_unknown_name_error():
    with pytest.raises(ValueError, match="unknown model"):
        load_model("gpt-42-enormous")


def test_local_checkpoint_roundtrip(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.T5Config(
        vocab_size=64, d_model=32, d_kv=8, d_ff=64, num_layers=1, num_decoder_layers=1, num_heads=4,
        dropout_rate=0.0,
    )
    torch.manual_seed(1)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg)
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    torch.save(hf_model.state_dict(), ckpt / "pytorch_model.bin")
    (ckpt / "config.json").write_text(json.dumps({**hf_cfg.to_dict(), "model_type": "t5"}))

    lm = load_model(str(ckpt))
    assert lm.params is not None
    ids = np.ones((1, 4), np.int32)
    logits = lm.module.apply({"params": lm.params}, ids, np.ones_like(ids), ids)
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.ones(1, 4, dtype=torch.long),
            attention_mask=torch.ones(1, 4, dtype=torch.long),
            decoder_input_ids=torch.ones(1, 4, dtype=torch.long),
        ).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=1e-4, rtol=1e-3)


def test_sharded_init_on_mesh(mesh8):
    """Params initialized then sharded by the default rules on an 8-device mesh."""
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    lm = load_model("t5-test")
    params = lm.init_params(0)
    sharded = shard_params(params, mesh8)
    emb = sharded["shared"]["embedding"]  # (256, 64): vocab over tensor*fsdp=4, d replicated
    assert {s.data.shape for s in emb.addressable_shards} == {(64, 64)}
    assert sorted(T5_CONFIGS) == ["flan-t5-xl", "t5-base", "t5-large", "t5-small", "t5-test"]


def test_attention_impl_flag_reaches_config():
    """--attention-impl threads CLI → TrainConfig → load_model → model
    config for every family (T5 included since its flash path landed)."""
    from distributed_llms_example_tpu.core.config import TrainConfig
    from distributed_llms_example_tpu.models.registry import load_model

    assert TrainConfig().attention_impl == ""  # default: model's own choice
    for name in ("t5-test", "bart-test", "llama-test"):
        lm = load_model(name, attention_impl="xla")
        assert lm.config.attention_impl == "xla", name
        lm = load_model(name)
        assert lm.config.attention_impl == "auto", name
