"""Known-bad fixture: the rank-varying retry count before a collective
(the PR 15 review bug).  The candidate ladder is enumerated from the
LOCAL filesystem, so a rank whose disk lags (or whose listing raced a
GC) runs a different number of restore attempts — each attempt a
collective its peers may never join.

The fixed production shape (io/checkpoint.py ``_agreed_count``): the
attempt count is MAX-agreed over the heartbeat channel and short ranks
repeat their last candidate, keeping the per-attempt agreement sequence
aligned across the pod.
"""

import os


def restore_ladder(ckpt, abstract_state, ckpt_dir):
    candidates = sorted(os.listdir(ckpt_dir), reverse=True)
    for step in candidates:
        # BUG: trip count differs per rank — a collective per attempt
        state = ckpt.restore_before(abstract_state, int(step))
        if state is not None:
            return state
    return None
