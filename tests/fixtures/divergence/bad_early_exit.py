"""Known-bad fixture: the rank-divergent early return.  Non-zero ranks
bail out of the export before the save; process 0 then parks alone
inside the checkpoint commit collective.

The fixed production shape: gather first (every rank participates),
THEN gate the local file write on process_index — never the other way
around.
"""

import jax


def export_checkpoint(ckpt, step, state):
    if jax.process_index() != 0:
        return
    # BUG: only p0 reaches the commit collective
    ckpt.save(step, state)
