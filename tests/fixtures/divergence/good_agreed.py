"""Known-good fixture: the same recovery shapes as the bad_* files, but
routed through the agreement sanitizers — the patterns io/checkpoint.py
actually ships.  The divergence pass must report ZERO findings here; a
finding on this file is an analyzer regression (false positive), exactly
as a silent pass on a bad_* file is a missed bug.
"""

import os

import jax


def restore_with_agreed_walkback(ckpt, abstract_state, step):
    """The fixed exception walk-back: capture, MIN-agree, act together."""
    state, err = None, None
    try:
        state = ckpt.restore_latest(abstract_state)
    except Exception as e:
        err = e
    if not ckpt._agreed_ok(err is None):
        # every rank takes this branch together: the verdict is pod-agreed
        return ckpt.restore_before(abstract_state, step)
    return state


def verify_then_restore_broadcast(ckpt, verify, abstract_state, step):
    """The fixed p0-only verify: the verdict rides the heartbeat channel."""
    chosen = None
    if jax.process_index() == 0:
        chosen = step if verify(step) is None else None
    chosen = ckpt._agreed_step(chosen)
    if chosen is None:
        return ckpt.restore_before(abstract_state, step)
    return ckpt.restore_latest(abstract_state)


def restore_ladder_agreed(ckpt, abstract_state, ckpt_dir):
    """The fixed fallback ladder: MAX-agreed attempt count, short ranks
    repeat their last candidate."""
    candidates = sorted(os.listdir(ckpt_dir), reverse=True)
    n_attempts = ckpt._agreed_count(len(candidates))
    while len(candidates) < n_attempts:
        candidates.append(candidates[-1] if candidates else "0")
    for i in range(n_attempts):
        # the trip count is the AGREED count: candidate VALUES may differ
        # per rank, but every rank runs the same collective sequence and
        # the per-attempt MIN verdict keeps the pod in lockstep
        state, err = None, None
        try:
            state = ckpt.restore_before(abstract_state, int(candidates[i]))
        except Exception as e:
            err = e
        if ckpt._agreed_ok(err is None and state is not None):
            return state
    return None


def gather_then_export(ckpt, gather_tree, step, state):
    """The fixed p0 export: collective first, rank gate second."""
    host_state = gather_tree(state)
    if jax.process_index() != 0:
        return
    with open(f"export-{step}.json", "w") as fh:
        fh.write(str(type(host_state).__name__))
