"""Known-bad fixture: the p0-only unbroadcast verdict (the PR 14 review
bug).  Process 0 verifies the checkpoint and flips ``ok`` — but the
verdict is never broadcast, so every other rank still holds the default.
The ranks then take DIFFERENT branches into the restore collective.

The fixed production shape (io/checkpoint.py ``_agreed_step``): p0's
verdict rides the heartbeat allgather channel; row 0 IS the verdict on
every rank.
"""

import jax


def verify_then_restore(ckpt, verify, abstract_state, step):
    ok = True
    if jax.process_index() == 0:
        ok = verify(step)
    if not ok:
        # BUG: `ok` is rank-divergent — p0's verdict was never broadcast
        return ckpt.restore_before(abstract_state, step)
    return ckpt.restore_latest(abstract_state)
