"""Known-bad fixture: the one-rank exception walk-back (the PR 6 review
bug).  A restore that raises on ONE rank sends only that rank into the
walk-back collective; its peers, whose restore succeeded, have already
returned — the pod deadlocks inside ``restore_before``.

The fixed production shape (io/checkpoint.py): capture the error, agree
on ``err is None`` with the MIN helper, and walk back TOGETHER.
"""


def restore_with_walkback(ckpt, abstract_state, step):
    try:
        return ckpt.restore_latest(abstract_state)
    except Exception:
        # BUG: only the throwing rank reaches this collective
        return ckpt.restore_before(abstract_state, step)
