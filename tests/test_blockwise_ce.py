"""Blockwise (vocab-chunked) cross-entropy vs the materialized-logits path.

The fused path must reproduce the standard ``cross_entropy_sums`` on
bf16-rounded logits up to the fp32-vs-bf16 accumulation difference it
deliberately improves on — values and gradients for BOTH inputs (hidden
and the LM-head kernel), with and without label smoothing — and slot into
the train step via ``--fused-ce`` with matching loss/grad-norm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.ops.blockwise_ce import (
    blockwise_cross_entropy_sums,
    pick_block,
)
from distributed_llms_example_tpu.train.step import cross_entropy_sums

jax.config.update("jax_default_matmul_precision", "highest")


def _case(seed=0, N=24, D=16, V=105):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(N, D) * 0.5, jnp.float32)
    w = jnp.asarray(rng.randn(D, V) * 0.2, jnp.float32)
    labels = rng.randint(0, V, (N,)).astype(np.int32)
    labels[:5] = LABEL_PAD
    return h, w, jnp.asarray(labels)


def test_pick_block_divides():
    for v in (105, 32000, 50265, 7, 4096):
        b = pick_block(v)
        assert v % b == 0 and b >= 1


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_matches_materialized_logits(smoothing):
    h, w, labels = _case()

    def ref(h, w):
        logits = (h @ w)[None]  # cross_entropy_sums expects (B, S, V)
        return cross_entropy_sums(logits, labels[None, :], smoothing)

    def fused(h, w):
        return blockwise_cross_entropy_sums(h, w, labels, smoothing, 15)

    l1, t1 = fused(h, w)
    lr, tr = ref(h, w)
    assert float(t1) == float(tr)
    np.testing.assert_allclose(float(l1), float(lr), rtol=1e-5)

    gh_r, gw_r = jax.grad(lambda h, w: ref(h, w)[0], argnums=(0, 1))(h, w)
    gh_f, gw_f = jax.grad(lambda h, w: fused(h, w)[0], argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_r), atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r), atol=1e-5, rtol=1e-4)


def test_all_masked_rows_are_safe():
    h, w, labels = _case()
    labels = jnp.full_like(labels, LABEL_PAD)
    lsum, tokens = blockwise_cross_entropy_sums(h, w, labels)
    assert float(tokens) == 0.0 and float(lsum) == 0.0
    gh = jax.grad(lambda h: blockwise_cross_entropy_sums(h, w, labels)[0])(h)
    assert np.isfinite(np.asarray(gh)).all()
    assert float(jnp.sum(jnp.abs(gh))) == 0.0


def test_train_step_with_fused_ce_matches_unfused():
    """--fused-ce through the real train step: loss / token count /
    grad-norm match the unfused step on a tiny llama (fp32 so the only
    difference is the fused path's better logit accumulation)."""
    import dataclasses

    import optax

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    cfg = LlamaConfig(
        vocab_size=120, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, max_position_embeddings=64,
    )
    module = LlamaForCausalLM(cfg)
    params0 = jax.device_get(
        module.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    )
    rng = np.random.RandomState(5)
    b, s = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, s), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    mesh = build_mesh(MeshConfig(data=1), devices=jax.devices()[:1])

    def run(model_cfg):
        m = LlamaForCausalLM(model_cfg)
        state = create_train_state(shard_params(params0, mesh), tx)
        state = jax.tree.map(
            lambda x, sh: jax.device_put(x, sh), state, state_shardings(state, mesh)
        )
        build = make_train_step(m, model_cfg, tx, lambda s: 1e-2, mesh, donate=False, is_seq2seq=False)
        step, _ = build(state)
        _, metrics = step(state, put_batch(batch, mesh))
        return metrics

    ref = run(cfg)
    got = run(dataclasses.replace(cfg, fused_ce=True))
    assert float(got["target_tokens"]) == float(ref["target_tokens"])
    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)


def test_fused_ce_mesh_and_family_validation(tmp_path):
    """--fused-ce must fail loudly at Trainer startup on the compositions
    it documents as unsupported (tensor/stage/sequence meshes, seq2seq
    families) instead of silently degrading or being inert."""
    from distributed_llms_example_tpu.core.config import (
        CheckpointConfig,
        MeshConfig,
        TrainConfig,
    )
    from distributed_llms_example_tpu.train.trainer import Trainer

    records = [{"dialogue": "a b c d", "summary": "a b"} for _ in range(8)]
    base = dict(
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=16,
        tokenizer="byte",
        fused_ce=True,
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
    )
    with pytest.raises(ValueError, match="seq2seq"):
        Trainer(
            TrainConfig(model_ckpt="bart-test", mesh=MeshConfig(data=-1), **base),
            train_records=records,
        )
    with pytest.raises(ValueError, match="tensor"):
        Trainer(
            TrainConfig(
                model_ckpt="llama-test",
                mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
                **base,
            ),
            train_records=records,
        )
