"""Flash attention (Pallas, interpret mode on CPU) vs XLA attention.

Checks forward numerics and gradients of the blockwise online-softmax
kernel against ``dot_product_attention`` — the property the reference never
tests for its cuDNN attention (SURVEY.md §4: no tests at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.ops.attention import (
    dot_product_attention,
    make_causal_bias,
    mask_to_bias,
)
from distributed_llms_example_tpu.ops.flash_attention import (
    flash_attention,
    flash_supported,
)

B, H, D = 2, 3, 32


def _qkv(q_len=256, kv_len=256, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32), dtype)  # noqa: E731
    return mk(B, H, q_len, D), mk(B, H, kv_len, D), mk(B, H, kv_len, D)


def test_forward_matches_xla():
    q, k, v = _qkv()
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_causal():
    q, k, v = _qkv(256, 256)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v, bias=make_causal_bias(256, 256))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_padding_bias():
    q, k, v = _qkv(128, 256)
    mask = np.ones((B, 256), np.int32)
    mask[0, 100:] = 0
    mask[1, 37:] = 0
    bias = mask_to_bias(jnp.asarray(mask))  # (B, 1, 1, K)
    out = flash_attention(q, k, v, bias, block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_full_bias_bf16():
    q, k, v = _qkv(128, 128, dtype=jnp.bfloat16)
    rng = np.random.RandomState(1)
    bias = jnp.asarray(rng.randn(1, H, 128, 128).astype(np.float32))
    out = flash_attention(q, k, v, bias, block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v, bias)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


@pytest.mark.parametrize(
    "causal,q_len,kv_len",
    [
        (False, 128, 128),
        (True, 128, 128),
        # the rectangular case exercises the bwd kernels with nq != nk (BART
        # cross-attention shape); causal+rectangular is rejected by contract
        (False, 64, 128),
    ],
)
def test_gradients_match(causal, q_len, kv_len):
    q, k, v = _qkv(q_len, kv_len)
    mask = np.ones((B, kv_len), np.int32)
    mask[0, kv_len - 38 :] = 0
    bias = mask_to_bias(jnp.asarray(mask))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, bias, causal=causal, block_q=32, block_k=64) ** 2
        )

    def loss_ref(q, k, v):
        full = bias + (make_causal_bias(q_len, kv_len) if causal else 0.0)
        return jnp.sum(dot_product_attention(q, k, v, full) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3)


def test_causal_requires_square():
    """causal=True with q_len != kv_len is ambiguous (top-left vs decode
    bottom-right alignment) and must be rejected, not silently mis-masked."""
    q, k, v = _qkv(64, 128)
    with pytest.raises(ValueError, match="square self-attention"):
        flash_attention(q, k, v, causal=True, block_q=32, block_k=64)


def test_grad_under_jit_and_vmap_free_shapes():
    q, k, v = _qkv(128, 128)

    @jax.jit
    def f(q, k, v):
        return jnp.mean(flash_attention(q, k, v, causal=True))

    g = jax.grad(f)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()


def test_flash_supported():
    assert flash_supported(1024, 1024, 64)
    assert flash_supported(128, 256, 64)
    assert not flash_supported(100, 128, 64)  # not divisible
    assert not flash_supported(4, 4, 64)  # too small
    assert not flash_supported(128, 128, 65)  # odd head dim


def test_rejects_bad_shapes():
    q, k, v = _qkv(100, 100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v)


def test_auto_block_selection():
    """Largest 16-aligned divisor in [128, 512] — big tiles for the bench shapes,
    graceful degradation for odd-but-divisible lengths."""
    from distributed_llms_example_tpu.ops.flash_attention import auto_block, flash_supported

    assert auto_block(1024) == 512
    assert auto_block(512) == 512
    assert auto_block(128) == 128
    assert auto_block(640) == 320  # not divisible by 512; previously 128-tiled
    assert auto_block(64) == 64  # short sequence: one seq-sized tile
    assert auto_block(136) == 0  # no 16-aligned divisor ≥ 128 → XLA fallback
    assert auto_block(1048) == 0  # 8*131: tiny tiles would drown in grid overhead
    assert auto_block(100) == 0
    assert auto_block(7) == 0
    assert flash_supported(640, 640, 64)
    assert not flash_supported(7, 7, 64)
    assert not flash_supported(1048, 1048, 64)


def test_noncausal_block_cap():
    """Non-causal attention without a learned bias tiles up to 1024 (measured
    faster on v5e); causal stays at 512, and learned-bias caps block_q at
    512 (dlbias VMEM) while its block_k may reach 1024."""
    from distributed_llms_example_tpu.ops.flash_attention import (
        MAX_BLOCK,
        MAX_BLOCK_NONCAUSAL,
        auto_block,
    )

    assert MAX_BLOCK == 512 and MAX_BLOCK_NONCAUSAL == 1024
    assert auto_block(1024, MAX_BLOCK_NONCAUSAL) == 1024
    assert auto_block(2048, MAX_BLOCK_NONCAUSAL) == 1024
    assert auto_block(512, MAX_BLOCK_NONCAUSAL) == 512
    # flash_supported mirrors the per-path caps: 592 = 16*37 tiles only
    # above 512, so it is eligible non-causal but NOT causal; learned-bias
    # caps block_q at 512 (dlbias VMEM) while block_k may reach 1024
    assert flash_supported(592, 592, 64)
    assert not flash_supported(592, 592, 64, causal=True)
    assert not flash_supported(592, 592, 64, has_learned_bias=True)
    assert flash_supported(512, 592, 64, has_learned_bias=True)
    # correctness at the 1024 tile, interpret-mode (CPU): square + cross
    rng = np.random.RandomState(3)
    for q_len in (1024, 128):
        q = jnp.asarray(rng.randn(1, 2, q_len, 32), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, 1024, 32), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, 1024, 32), jnp.float32)
        got = flash_attention(q, k, v, causal=False)
        want = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # gradients through the 1024-tile bwd kernels (dq/dkv grids run ONE
    # k/q block each at this size — the production bart encoder shape)
    q = jnp.asarray(rng.randn(1, 2, 1024, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 1024, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 1024, 32), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=False) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)


def test_lbias_asymmetric_tiles_grad_parity():
    """The learned-bias default tiling is now ASYMMETRIC (block_q capped at
    512, block_k at 1024) — run its backward (dq/dkv/dlbias kernels) with
    block_k > block_q and check gradients against plain attention."""
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
    lb = jnp.asarray(rng.randn(1, 2, 64, 128).astype(np.float32) * 0.1)

    def loss_flash(q, k, v, lb):
        out = flash_attention(q, k, v, learned_bias=lb, block_q=64, block_k=128)
        return jnp.sum(out ** 2)

    def loss_ref(q, k, v, lb):
        return jnp.sum(dot_product_attention(q, k, v, lb) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, lb)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, lb)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)


def test_parity_non_pow2_length():
    """Auto-blocked parity at a length divisible by neither 128 nor 512."""
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(2, 2, 320, 16).astype(np.float32)) for _ in range(3))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = dot_product_attention(q, k, v, make_causal_bias(320, 320))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fully_masked_rows_give_finite_zero_grads():
    """The advisor's edge: causal attention plus an additive -inf padding
    bias that masks EVERY key of example 0.  Its rows' only finite scores
    are the causally-masked MASK_VALUE entries, so the saved lse lands at
    ~MASK_VALUE; the backward kernels must zero p for such rows (lse at the
    sentinel scale) or they contribute garbage — potentially inf/NaN once a
    learned bias shifts s — to the batch-summed learned-bias gradient.
    Dead-example grads must be exactly zero and the live example's grads
    (and the summed dlbias) must equal a run without the dead example."""
    q_len = kv_len = 64
    q, k, v = _qkv(q_len, kv_len)
    mask = np.ones((B, kv_len), np.float32)
    mask[0, :] = 0  # example 0: every key masked
    bias = jnp.where(jnp.asarray(mask)[:, None, None, :] > 0, 0.0, -jnp.inf)
    rng = np.random.RandomState(2)
    lbias = jnp.asarray(rng.randn(1, H, q_len, kv_len).astype(np.float32) * 0.1)

    def loss(q, k, v, lbias, bias):
        return jnp.sum(
            flash_attention(
                q, k, v, bias, learned_bias=lbias, causal=True, block_q=32, block_k=32
            )
            ** 2
        )

    # the dead example's FORWARD output must be exact zeros (not an
    # average of v over causally-forbidden positions)
    out = flash_attention(
        q, k, v, bias, learned_bias=lbias, causal=True, block_q=32, block_k=32
    )
    np.testing.assert_array_equal(np.asarray(out[0]), 0.0)
    assert np.isfinite(np.asarray(out)).all()

    g_full = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, lbias, bias)
    for g in g_full:
        assert np.isfinite(np.asarray(g)).all(), "NaN/inf gradient from fully-masked rows"
    # the dead example contributes nothing to its own q/k/v grads...
    for g in g_full[:3]:
        np.testing.assert_array_equal(np.asarray(g[0]), 0.0)
    # ...and nothing to the batch-summed learned-bias grad: grads must
    # match a run over the live examples only
    g_live = jax.grad(loss, argnums=(0, 1, 2, 3))(
        q[1:], k[1:], v[1:], lbias, bias[1:]
    )
    for a, b in zip(g_full[:3], g_live[:3]):
        np.testing.assert_allclose(np.asarray(a[1:]), np.asarray(b), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(g_full[3]), np.asarray(g_live[3]), atol=1e-5
    )


def test_causal_cap_is_head_dim_dependent():
    """Causal tiles cap at 512 for narrow heads (d=64: diagonal masked work
    dominates wider tiles) but 1024 at d>=128 (7B regime, measured -17%/-24%
    fwd+bwd at batch 4/8 — BENCH_7B_r05.json attack A)."""
    from distributed_llms_example_tpu.ops.flash_attention import _block_caps

    assert _block_caps(True, False, 64) == (512, 512)
    assert _block_caps(True, False, 128) == (1024, 1024)
    # 592 = 16*37 tiles only above 512: causal+wide heads becomes eligible
    assert flash_supported(592, 592, 128, causal=True)
    assert not flash_supported(592, 592, 64, causal=True)


def test_beam_grouped_attention_matches_replicated_kv():
    """The beam-decode grouped path (K/V shared per row) must reproduce
    plain attention on per-beam-replicated K/V exactly — same fp32
    softmax, scale, bias conventions (ops/attention.py)."""
    import jax.numpy as jnp

    from distributed_llms_example_tpu.ops.attention import (
        beam_grouped_attention,
        dot_product_attention,
    )

    rng = np.random.RandomState(9)
    B, G, H, Q, K, d = 3, 2, 4, 1, 16, 8
    q = jnp.asarray(rng.randn(B * G, H, Q, d).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, K, d).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, K, d).astype(np.float32))
    bias = jnp.asarray(
        np.where(rng.rand(B, 1, 1, K) < 0.2, -1e9, 0.0).astype(np.float32)
    )
    # per-beam bias: each row's mask repeated per beam (the generation layout)
    bias_rep = jnp.repeat(bias, G, axis=0)
    k_rep = jnp.repeat(k, G, axis=0)
    v_rep = jnp.repeat(v, G, axis=0)

    ref = dot_product_attention(q, k_rep, v_rep, bias_rep)
    got = beam_grouped_attention(q, k, v, bias_rep)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6, rtol=1e-6)
    # unscaled + learned-bias variant (the T5 cross path)
    lb = jnp.asarray(rng.randn(1, H, Q, K).astype(np.float32) * 0.1)
    ref2 = dot_product_attention(q, k_rep, v_rep, bias_rep + lb, scale=1.0)
    got2 = beam_grouped_attention(q, k, v, bias_rep, scale=1.0, learned_bias=lb)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref2), atol=1e-6, rtol=1e-6)
