"""Sharding-rule tests: specs resolve, arrays actually land sharded."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_llms_example_tpu.parallel.sharding import (
    batch_sharding,
    default_rules,
    infer_param_shardings,
    shard_params,
)


def _fake_params():
    return {
        "shared": {"embedding": jnp.zeros((64, 32))},
        "encoder": {
            "block_0": {
                "self_attn": {
                    "q_proj": {"kernel": jnp.zeros((32, 32))},
                    "o_proj": {"kernel": jnp.zeros((32, 32))},
                },
                "mlp": {
                    "wi": {"kernel": jnp.zeros((32, 128))},
                    "wo": {"kernel": jnp.zeros((128, 32))},
                },
                "norm": {"scale": jnp.ones((32,))},
            }
        },
    }


def test_rule_specs():
    rules = default_rules()
    specs = rules.tree_specs(_fake_params())
    assert specs["shared"]["embedding"] == P(("tensor", "fsdp"), None)
    blk = specs["encoder"]["block_0"]
    assert blk["self_attn"]["q_proj"]["kernel"] == P("fsdp", "tensor")
    assert blk["self_attn"]["o_proj"]["kernel"] == P("tensor", "fsdp")
    assert blk["mlp"]["wi"]["kernel"] == P("fsdp", "tensor")
    assert blk["mlp"]["wo"]["kernel"] == P("tensor", "fsdp")
    assert blk["norm"]["scale"] == P()


def test_spec_clipped_to_rank():
    rules = default_rules()
    # a 1-D array matching a 2-D rule must get the spec truncated, not crash:
    # P("fsdp", "tensor") clipped to rank 1 → P("fsdp")
    assert rules.spec_for("encoder/block_0/self_attn/q_proj/kernel", 1) == P("fsdp")
    # unmatched paths fall through to the replicated default
    assert rules.spec_for("encoder/block_0/self_attn/q_proj/bias", 1) == P()


def test_shard_params_places_arrays(mesh8):
    params = _fake_params()
    sharded = shard_params(params, mesh8)
    emb = sharded["shared"]["embedding"]
    # vocab dim split over tensor*fsdp = 4, d_model replicated
    shard_shapes = {s.data.shape for s in emb.addressable_shards}
    assert shard_shapes == {(16, 32)}
    # replicated norm scale: every shard is the full array
    scale = sharded["encoder"]["block_0"]["norm"]["scale"]
    assert {s.data.shape for s in scale.addressable_shards} == {(32,)}


def test_batch_sharding_runs_collective(mesh8):
    """A jitted mean over a batch sharded on (data, fsdp) must equal the
    host-side mean — exercises the partitioner-inserted all-reduce that
    replaces the reference's hand-rolled average_gradients."""
    bs = batch_sharding(mesh8)
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    xs = jax.device_put(x, bs)
    got = jax.jit(lambda a: jnp.mean(a * 2.0))(xs)
    np.testing.assert_allclose(np.asarray(got), (x * 2.0).mean(), rtol=1e-6)


def test_ragged_dim_falls_back_to_replicated(mesh8):
    """bart-large-cnn's vocab is 50265 (odd): the (tensor, fsdp) vocab split
    can't divide it, so spec resolution must drop that dim to replicated
    instead of letting device_put crash (divisible dims still shard)."""
    from distributed_llms_example_tpu.parallel.sharding import (
        divisible_spec,
        infer_param_shardings,
    )

    assert divisible_spec(P(("tensor", "fsdp"), None), (50265, 1024), mesh8) == P(None, None)
    assert divisible_spec(P(("tensor", "fsdp"), None), (50264, 1024), mesh8) == P(("tensor", "fsdp"), None)
    assert divisible_spec(P("fsdp", "tensor"), (6, 8), mesh8) == P("fsdp", "tensor")

    params = {"shared": {"embedding": np.zeros((15, 32), np.float32)}}
    sh = infer_param_shardings(params, mesh8)
    assert sh["shared"]["embedding"].spec == P(None, None)
